"""Core-runtime microbenchmarks (the ``ray_perf.py`` equivalent).

Measures the framework-overhead envelope the way the reference's
microbenchmark suite does (``python/ray/_private/ray_perf.py:93-315``, run
nightly via ``release/microbenchmark/``): tasks/s sync+async, actor calls/s
1:1 and n:n, object put/get throughput, many-ref ``wait``, and cross-node
transfer. Prints one JSON line per metric and a summary table; run with

    python microbench.py [--quick]

Results are committed to ``MICROBENCH.md`` alongside BASELINE.md's envelope
rows so every round tracks framework overhead, not just model FLOPs.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def timeit(name, fn, n, unit="ops/s"):
    t0 = time.perf_counter()
    fn()
    dt = time.perf_counter() - t0
    rate = n / dt
    print(json.dumps({"metric": name, "value": round(rate, 1), "unit": unit,
                      "n": n, "seconds": round(dt, 3)}), flush=True)
    return name, rate, unit


def _bench_rpc(results, q):
    """Raw transport rows (no cluster): framed-pickle RPC throughput over
    the reactor write path, and the stalled-peer head-of-line bound —
    a peer that requests a multi-MB inline reply and never reads it must
    not stall other connections (the reply parks in its own per-conn
    outbound queue; the old blocking-sendall design froze the reactor
    for up to 15 s per stalled reply)."""
    import socket as _socket

    from ray_tpu.core.rpc import _LEN, RpcClient, RpcServer, dumps

    srv = RpcServer({"ping": lambda: "pong",
                     "blob": lambda n: b"x" * n},
                    name="bench", inline_methods={"ping", "blob"})
    try:
        cli = RpcClient(srv.addr)
        n = 1000 if q else 10000
        results.append(timeit(
            "rpc_inline_calls_per_s",
            lambda: [cli.call("ping") for _ in range(n)], n))

        stalled = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        stalled.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, 4096)
        stalled.connect(srv.addr)
        req = dumps({"id": 1, "method": "blob", "args": (8 << 20,)})
        stalled.sendall(_LEN.pack(len(req)) + req)
        time.sleep(0.3)  # let the reactor queue the 8 MiB reply
        lat = []
        for _ in range(50 if q else 200):
            t0 = time.perf_counter()
            cli.call("ping", timeout=30.0)
            lat.append(time.perf_counter() - t0)
        worst = max(lat) * 1e3
        print(json.dumps({"metric": "rpc_ping_ms_while_peer_stalled",
                          "value": round(worst, 2), "unit": "ms (max)",
                          "n": len(lat)}), flush=True)
        results.append(("rpc_ping_ms_while_peer_stalled", worst, "ms (max)"))
        stalled.close()
        cli.close()
    finally:
        srv.stop()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="small iteration counts (CI smoke)")
    args = parser.parse_args()
    q = args.quick

    import ray_tpu

    results = []
    _bench_rpc(results, q)

    ray_tpu.init(num_cpus=8)

    @ray_tpu.remote
    def nop():
        return None

    @ray_tpu.remote
    def nop_arg(x):
        return x

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    # Warm the worker pool (first task forks + imports).
    ray_tpu.get([nop.remote() for _ in range(8)])

    n = 40 if q else 300
    results.append(timeit(
        "tasks_sync_per_s",
        lambda: [ray_tpu.get(nop.remote()) for _ in range(n)], n))

    n = 200 if q else 3000
    results.append(timeit(
        "tasks_async_batch_per_s",
        lambda: ray_tpu.get([nop.remote() for _ in range(n)]), n))

    actor = Counter.options(num_cpus=0).remote()
    ray_tpu.get(actor.inc.remote())
    n = 50 if q else 500
    results.append(timeit(
        "actor_calls_sync_1_1_per_s",
        lambda: [ray_tpu.get(actor.inc.remote()) for _ in range(n)], n))

    n = 300 if q else 5000
    results.append(timeit(
        "actor_calls_async_1_1_per_s",
        lambda: ray_tpu.get([actor.inc.remote() for _ in range(n)]), n))

    actors = [Counter.options(num_cpus=0).remote() for _ in range(8)]
    ray_tpu.get([a.inc.remote() for a in actors])
    n = 400 if q else 8000
    results.append(timeit(
        "actor_calls_async_n_n_per_s",
        lambda: ray_tpu.get([actors[i % 8].inc.remote() for i in range(n)]),
        n))

    size = (64 if q else 1024) * 1024 * 1024 // 1024  # MiB scale below
    mb = 64 if q else 1024
    blob = np.random.default_rng(0).integers(
        0, 255, size=(mb * 1024 * 1024,), dtype=np.uint8)
    t0 = time.perf_counter()
    ref = ray_tpu.put(blob)
    dt = time.perf_counter() - t0
    put_rate = blob.nbytes / dt / 1e9
    print(json.dumps({"metric": "put_GB_per_s", "value": round(put_rate, 2),
                      "unit": "GB/s", "bytes": blob.nbytes}), flush=True)
    results.append(("put_GB_per_s", put_rate, "GB/s"))

    t0 = time.perf_counter()
    got = ray_tpu.get(ref)
    dt = time.perf_counter() - t0
    get_rate = got.nbytes / dt / 1e9
    print(json.dumps({"metric": "get_GB_per_s", "value": round(get_rate, 2),
                      "unit": "GB/s", "bytes": got.nbytes}), flush=True)
    results.append(("get_GB_per_s", get_rate, "GB/s"))
    del got, blob, ref

    n = 200 if q else 1000
    refs = [nop_arg.remote(i) for i in range(n)]
    t0 = time.perf_counter()
    ready, pending = ray_tpu.wait(refs, num_returns=n, timeout=60.0)
    dt = time.perf_counter() - t0
    print(json.dumps({"metric": "wait_1k_refs_s", "value": round(dt, 3),
                      "unit": "s", "ready": len(ready)}), flush=True)
    results.append(("wait_1k_refs_s", dt, "s"))
    del refs, ready, pending

    # Cross-node transfer: second in-process node, task pinned there
    # produces a block, driver pulls it chunked.
    from ray_tpu.core.api import _local_cluster
    from ray_tpu.core.node import Node

    controller, _head = _local_cluster
    side = Node(controller.address, {"CPU": 2.0, "side": 2.0})
    try:
        mb = 32 if q else 256

        @ray_tpu.remote(num_cpus=0, resources={"side": 1})
        def make(mbs):
            return np.zeros(mbs * 1024 * 1024, dtype=np.uint8)

        ref = make.remote(mb)
        ray_tpu.wait([ref], timeout=120.0)
        t0 = time.perf_counter()
        got = ray_tpu.get(ref, timeout=300.0)
        dt = time.perf_counter() - t0
        rate = got.nbytes / dt / 1e9
        print(json.dumps({"metric": "cross_node_get_GB_per_s",
                          "value": round(rate, 2), "unit": "GB/s",
                          "bytes": got.nbytes}), flush=True)
        results.append(("cross_node_get_GB_per_s", rate, "GB/s"))
        del got, ref
    finally:
        side.stop()

    print("\n| metric | value | unit |\n|---|---|---|")
    for name, rate, unit in results:
        print(f"| {name} | {rate:,.1f} | {unit} |")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
