"""Llama-family decoder-only transformer, TPU-first.

The flagship model for the framework's training/serving paths (the reference
has no model library — its benchmarks wrap torch models; our north-star is
Llama-2-7B pretraining at >=40% MFU, BASELINE.md). Design choices driven by
the TPU/XLA execution model:

* **Pure functional**: params are a pytree of arrays + a parallel pytree of
  logical axis names (``ray_tpu.parallel.sharding``); one rule table turns
  the same model into DP, FSDP, TP, SP or any mix — no model code changes.
* **Scanned layers**: all decoder layers live in one stacked pytree with a
  leading ``layers`` axis, executed by ``lax.scan`` — one layer is compiled
  once instead of L times (compile time and HLO size stay flat as depth
  grows), and ``jax.checkpoint`` on the scanned body gives the standard
  FSDP-friendly remat schedule.
* **bf16 compute, fp32 accumulation**: matmuls run in bf16 on the MXU with
  fp32 ``preferred_element_type`` where it matters (attention stats, loss);
  master params stay fp32 (cast per step).
* **Static shapes everywhere**; causal masking via position arithmetic so
  ring attention (sequence parallelism) composes by offset, not by mask
  materialization.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ray_tpu.ops.attention import attention
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rotary import apply_rope, rope_frequencies
from ray_tpu.parallel.sharding import constrain


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    mlp_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # Attention implementation: "xla" | "chunked" | "flash" (fused Pallas
    # kernel) | "ring" (requires a seq-sharded mesh context).
    attention_impl: str = "xla"
    remat: bool = True
    # Remat policy: "full" recomputes everything (min memory); "dots" saves
    # matmul outputs and recomputes only elementwise ops; "names" saves the
    # two expensive per-layer intermediates (attention output, ffn hidden)
    # so the backward recomputes only cheap projections/elementwise — the
    # middle point that usually maximizes MFU within HBM on TPU.
    remat_policy: str = "full"
    # Cross-entropy in sequence chunks of this many tokens (0 = whole
    # sequence): avoids materializing the full fp32 (B,S,V) logits, the
    # single largest activation at small model sizes.
    loss_chunk: int = 0
    # Fuse q/k/v into one (E, H+2KV, D) projection and gate/up into one
    # (E, 2M): fewer, larger matmuls — higher MXU utilization on TPU
    # (MaxText-style fused projections).
    fused_qkv: bool = False
    fused_mlp: bool = False
    # Embedding lookup as chunked one-hot MATMULS instead of gather: the
    # gather's backward is a scatter-add over the vocab table, which on a
    # bandwidth-starved part costs ~18% of the whole train step; as matmuls
    # both directions ride the MXU (one-hot chunks are rematerialized in
    # the backward, never stored).
    embed_via_matmul: bool = False
    embed_chunk: int = 512
    # Mixture-of-Experts: replace the dense MLP with moe_experts experts
    # (top-k routing, expert-parallel over the mesh's ``expert`` axis).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def num_params(self) -> int:
        p = self.vocab_size * self.dim  # embed
        mlp_params = 3 * self.dim * self.mlp_dim
        if self.moe_experts:
            mlp_params = (self.moe_experts * 3 * self.dim * self.mlp_dim
                          + self.dim * self.moe_experts)
        per_layer = (
            2 * self.dim  # norms
            + self.dim * self.n_heads * self.head_dim
            + 2 * self.dim * self.n_kv_heads * self.head_dim
            + self.n_heads * self.head_dim * self.dim
            + mlp_params
        )
        p += self.n_layers * per_layer
        p += self.dim  # final norm
        p += self.dim * self.vocab_size  # lm head
        return p


# Reference shapes: Llama-2 family (meta-llama); "debug"/"160m" are test and
# bench scales for single-chip and virtual-mesh runs.
PRESETS: Dict[str, LlamaConfig] = {
    "debug": LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                         n_kv_heads=2, mlp_dim=128, max_seq_len=128),
    "160m": LlamaConfig(vocab_size=32000, dim=768, n_layers=12, n_heads=12,
                        n_kv_heads=12, mlp_dim=2048, max_seq_len=2048),
    "1b": LlamaConfig(vocab_size=32000, dim=2048, n_layers=22, n_heads=16,
                      n_kv_heads=8, mlp_dim=5632, max_seq_len=4096),
    "7b": LlamaConfig(),
    "13b": LlamaConfig(dim=5120, n_layers=40, n_heads=40, n_kv_heads=40,
                       mlp_dim=13824),
    "70b": LlamaConfig(dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                       mlp_dim=28672),
}


def config_for(name_or_config) -> LlamaConfig:
    if isinstance(name_or_config, LlamaConfig):
        return name_or_config
    return PRESETS[name_or_config]


# ------------------------------------------------------------------ params

def param_axes(config: Optional[LlamaConfig] = None) -> Dict[str, Any]:
    """Logical axis names, mirroring the params pytree structure."""
    c = config
    layers: Dict[str, Any] = {
        "attn_norm": ("layers", "embed"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "mlp_norm": ("layers", "embed"),
        "w_down": ("layers", "mlp", "embed"),
    }
    if c is not None and c.fused_qkv:
        layers["wqkv"] = ("layers", "embed", "heads", "head_dim")
    else:
        layers["wq"] = ("layers", "embed", "heads", "head_dim")
        layers["wk"] = ("layers", "embed", "kv_heads", "head_dim")
        layers["wv"] = ("layers", "embed", "kv_heads", "head_dim")
    if c is not None and c.moe_experts:
        layers["moe"] = {
            "router": ("layers", "embed", "expert_dim"),
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        }
    elif c is not None and c.fused_mlp:
        layers["w_gate_up"] = ("layers", "embed", "mlp")
    else:
        layers["w_gate"] = ("layers", "embed", "mlp")
        layers["w_up"] = ("layers", "embed", "mlp")
    return {
        "tok_embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def decode_param_axes(config: Optional[LlamaConfig] = None) -> Dict[str, Any]:
    """Logical axes for GSPMD *serving* (``sharding.DECODE_RULES``): like
    :func:`param_axes` but the two row-parallel projections — ``wo`` and
    ``w_down`` — are fully replicated. Their input dims are CONTRACTED, so
    sharding them would split a reduction across the mesh and break the
    decode plane's bit-exactness contract; every other projection shards
    an output dim (heads/kv_heads/mlp/vocab over "model") and keeps the
    single-chip reduction order."""
    axes = param_axes(config)
    layers = axes["layers"]
    layers["wo"] = ("layers", None, None, None)
    layers["w_down"] = ("layers", None, None)
    return axes


def init_params(config: LlamaConfig, key: jax.Array,
                dtype=jnp.float32) -> Dict[str, Any]:
    """Initialize master params (fp32 by default). Layer params are stacked
    with a leading ``layers`` axis for lax.scan."""
    c = config
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    std = 0.02

    def normal(key, shape, fan_in=None):
        scale = std if fan_in is None else (1.0 / math.sqrt(fan_in))
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    lk = jax.random.split(k_layers, 7)
    L, E, H, KV, D, M = (c.n_layers, c.dim, c.n_heads, c.n_kv_heads,
                         c.head_dim, c.mlp_dim)
    layers: Dict[str, Any] = {
        "attn_norm": jnp.ones((L, E), dtype),
        "wo": normal(lk[3], (L, H, D, E), fan_in=H * D),
        "mlp_norm": jnp.ones((L, E), dtype),
        "w_down": normal(lk[6], (L, M, E), fan_in=M),
    }
    if c.fused_qkv:
        layers["wqkv"] = normal(lk[0], (L, E, H + 2 * KV, D), fan_in=E)
    else:
        layers["wq"] = normal(lk[0], (L, E, H, D), fan_in=E)
        layers["wk"] = normal(lk[1], (L, E, KV, D), fan_in=E)
        layers["wv"] = normal(lk[2], (L, E, KV, D), fan_in=E)
    if c.moe_experts:
        nk = jax.random.split(lk[4], 4)
        X = c.moe_experts
        layers["moe"] = {
            "router": normal(nk[0], (L, E, X), fan_in=E),
            "w_gate": normal(nk[1], (L, X, E, M), fan_in=E),
            "w_up": normal(nk[2], (L, X, E, M), fan_in=E),
            "w_down": normal(nk[3], (L, X, M, E), fan_in=M),
        }
    elif c.fused_mlp:
        layers["w_gate_up"] = normal(lk[4], (L, E, 2 * M), fan_in=E)
    else:
        layers["w_gate"] = normal(lk[4], (L, E, M), fan_in=E)
        layers["w_up"] = normal(lk[5], (L, E, M), fan_in=E)
    return {
        "tok_embed": normal(k_embed, (c.vocab_size, E)),
        "layers": layers,
        "final_norm": jnp.ones((E,), dtype),
        "lm_head": normal(k_head, (E, c.vocab_size), fan_in=E),
    }


# ----------------------------------------------------------------- forward

def _decoder_layer(config: LlamaConfig, x, layer, cos, sin, q_offset):
    """One decoder block. ``x``: (B, S, E) in compute dtype."""
    c = config
    h = rms_norm(x, layer["attn_norm"], c.norm_eps)
    h = constrain(h, ("batch", "length", "act_embed"))

    if "wqkv" in layer:
        qkv = jnp.einsum("bse,ehd->bshd", h, layer["wqkv"].astype(h.dtype))
        q = qkv[:, :, :c.n_heads]
        k = qkv[:, :, c.n_heads:c.n_heads + c.n_kv_heads]
        v = qkv[:, :, c.n_heads + c.n_kv_heads:]
    else:
        q = jnp.einsum("bse,ehd->bshd", h, layer["wq"].astype(h.dtype))
        k = jnp.einsum("bse,ehd->bshd", h, layer["wk"].astype(h.dtype))
        v = jnp.einsum("bse,ehd->bshd", h, layer["wv"].astype(h.dtype))
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    q = constrain(q, ("batch", "length", "heads", "head_dim"))
    k = constrain(k, ("batch", "length", "kv_heads", "head_dim"))

    if c.attention_impl == "ring":
        from ray_tpu.parallel.ring_attention import ring_attention
        from ray_tpu.parallel.sharding import current_mesh

        mesh = current_mesh()
        if mesh is None:
            raise ValueError("attention_impl='ring' requires an axis_rules "
                             "context with a seq-sharded mesh")
        attn = ring_attention(q, k, v, mesh)
    elif c.attention_impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        attn = flash_attention(q, k, v, causal=True, q_offset=q_offset)
    else:
        attn = attention(q, k, v, causal=True, q_offset=q_offset,
                         impl=c.attention_impl)
    attn = checkpoint_name(attn, "attn_out")
    attn = constrain(attn, ("batch", "length", "attn_heads", "head_dim"))
    out = jnp.einsum("bshd,hde->bse", attn, layer["wo"].astype(h.dtype))
    x = x + constrain(out, ("batch", "length", "act_embed"))

    h2 = rms_norm(x, layer["mlp_norm"], c.norm_eps)
    if "moe" in layer:
        from ray_tpu.ops.moe import moe_ffn

        out, aux = moe_ffn(h2, layer["moe"], top_k=c.moe_top_k,
                           capacity_factor=c.moe_capacity_factor)
        out = constrain(out, ("batch", "length", "act_embed"))
        return x + out, aux
    if "w_gate_up" in layer:
        gate_up = jnp.einsum("bse,em->bsm", h2,
                             layer["w_gate_up"].astype(h2.dtype))
        gate, up = jnp.split(gate_up, 2, axis=-1)
    else:
        gate = jnp.einsum("bse,em->bsm", h2,
                          layer["w_gate"].astype(h2.dtype))
        up = jnp.einsum("bse,em->bsm", h2, layer["w_up"].astype(h2.dtype))
    ffn = jax.nn.silu(gate) * up
    ffn = checkpoint_name(ffn, "mlp_hidden")
    ffn = constrain(ffn, ("batch", "length", "mlp_hidden"))
    down = jnp.einsum("bsm,me->bse", ffn, layer["w_down"].astype(h2.dtype))
    return x + constrain(down, ("batch", "length", "act_embed")), jnp.zeros(
        (), jnp.float32)


def _embed_matmul(table: jax.Array, tokens: jax.Array,
                  chunk: int = 512) -> jax.Array:
    """Embedding gather expressed as chunked one-hot matmuls (see
    ``embed_via_matmul``). Each chunk's one-hot is built, multiplied, and
    (via checkpoint) rebuilt in the backward — the vocab-table gradient
    becomes ``one_hot^T @ dy`` matmuls instead of a scatter-add."""
    b, s = tokens.shape
    v, e = table.shape
    flat = tokens.reshape(-1)
    n = flat.shape[0]
    chunk = min(chunk, n)
    if n % chunk:
        # Largest divisor of n that fits the requested chunk: keeps the
        # one-hot buffer bounded for ANY (B, S) instead of silently
        # collapsing to a single n-sized chunk (a 3 GB one-hot at bench
        # scales).
        chunk = next(c for c in range(chunk, 0, -1) if n % c == 0)

    @jax.checkpoint
    def one_chunk(tok_c):
        onehot = jax.nn.one_hot(tok_c, v, dtype=table.dtype)
        return onehot @ table

    def body(_, tok_c):
        return None, one_chunk(tok_c)

    _, out = jax.lax.scan(body, None, flat.reshape(n // chunk, chunk))
    return out.reshape(b, s, e)


def remat_wrap(body, config: LlamaConfig):
    """Apply the config's remat policy to a scan body (shared by the full
    model and pipeline stages so policies never diverge)."""
    policy = None
    if config.remat_policy == "dots":
        policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    elif config.remat_policy == "names":
        policy = jax.checkpoint_policies.save_only_these_names(
            "attn_out", "mlp_hidden")
    return jax.checkpoint(body, prevent_cse=False, policy=policy)


def hidden_states(params: Dict[str, Any], tokens: jax.Array,
                  config: LlamaConfig) -> jax.Array:
    """Token ids (B, S) -> final-norm hidden states (B, S, E)."""
    c = config
    if c.embed_via_matmul:
        x = _embed_matmul(params["tok_embed"].astype(c.dtype), tokens,
                          chunk=c.embed_chunk)
    else:
        # All-gather the table BEFORE the lookup: left to itself XLA
        # gathers from the fsdp-sharded table and then cannot convert the
        # embed-sharded output to batch sharding on permuted-order meshes
        # (expert/dcn/multi-process) — spmd_partitioner falls back to
        # "Involuntary full rematerialization", replicating the whole
        # activation every step. One explicit table all-gather is the
        # cheap, local-lookup form of the same data movement.
        table = constrain(params["tok_embed"].astype(c.dtype),
                          (None, None))
        x = table[tokens]
    x = constrain(x, ("batch", "length", "act_embed"))
    cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)

    def body(carry, layer):
        x, aux_sum = carry
        x, aux = _decoder_layer(c, x, layer, cos, sin, 0)
        return (x, aux_sum + aux), None

    if c.remat:
        body = remat_wrap(body, c)
    (x, aux_sum), _ = jax.lax.scan(body, (x, jnp.zeros((), jnp.float32)),
                                   params["layers"])
    return rms_norm(x, params["final_norm"], c.norm_eps), aux_sum


def forward(params: Dict[str, Any], tokens: jax.Array,
            config: LlamaConfig) -> jax.Array:
    """Token ids (B, S) -> logits (B, S, V) in fp32."""
    c = config
    x, _aux = hidden_states(params, tokens, config)
    logits = jnp.einsum("bse,ev->bsv", x,
                        params["lm_head"].astype(c.dtype),
                        preferred_element_type=jnp.float32)
    return constrain(logits, ("batch", "length", "vocab"))


def _chunk_ce(x_c, targets_c, lm_head):
    """Cross entropy for one sequence chunk; logits never leave the chunk."""
    logits = jnp.einsum("bse,ev->bsv", x_c, lm_head,
                        preferred_element_type=jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets_c[..., None], axis=-1)[..., 0]
    return jnp.sum(logz - gold)


def loss_fn(params: Dict[str, Any], batch: Dict[str, jax.Array],
            config: LlamaConfig) -> jax.Array:
    """Next-token cross entropy. ``batch``: {"tokens": (B, S+1) int32} or
    {"inputs": (B, S), "targets": (B, S)}; fp32 log-softmax. With
    ``config.loss_chunk`` the (B,S,V) fp32 logits are never materialized —
    the head matmul + CE run per sequence chunk under remat."""
    c = config
    if "tokens" in batch:
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    x, aux = hidden_states(params, inputs, c)
    lm_head = params["lm_head"].astype(c.dtype)
    b, s, _ = x.shape
    chunk = c.loss_chunk
    if chunk and s % chunk == 0 and s > chunk:
        n = s // chunk
        x_chunks = x.reshape(b, n, chunk, -1).transpose(1, 0, 2, 3)
        t_chunks = targets.reshape(b, n, chunk).transpose(1, 0, 2)

        def body(total, xt):
            x_c, t_c = xt
            return total + jax.checkpoint(_chunk_ce)(x_c, t_c, lm_head), None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                (x_chunks, t_chunks))
        loss = total / (b * s)
        if c.moe_experts:
            loss = loss + c.moe_aux_coef * aux / c.n_layers
        return loss
    logits = jnp.einsum("bse,ev->bsv", x, lm_head,
                        preferred_element_type=jnp.float32)
    logits = constrain(logits, ("batch", "length", "vocab"))
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    loss = jnp.mean(logz - gold)
    if c.moe_experts:
        loss = loss + c.moe_aux_coef * aux / c.n_layers
    return loss


def flops_per_token(config: LlamaConfig, seq_len: int) -> float:
    """Training FLOPs/token (fwd+bwd ~= 6*N plus attention quadratic term)."""
    c = config
    param_flops = 6.0 * c.num_params()
    # attention scores+values: 2 matmuls * 2 (fwd) * 3 (fwd+bwd) per token:
    attn_flops = 12.0 * c.n_layers * c.n_heads * c.head_dim * seq_len
    return param_flops + attn_flops
