"""Autoregressive decoding for the Llama family: KV cache + jitted
prefill/decode steps + ``generate``.

The serving-side other half of ``models/llama.py`` (VERDICT r4 Missing #2;
reference: serving generation flows through the model-agnostic replica call
path ``python/ray/serve/_private/replica.py:231`` with streaming
``proxy.py:761`` — the reference has no model library, so its KV cache
lives in user code/vLLM; here it is TPU-native and first-class).

Design for the XLA/TPU execution model:

* **Static cache buckets**: the cache is a fixed ``(L, B, C, KV, D)``
  allocation (``C`` = a power-of-two-ish capacity bucket). One compiled
  program per (B, C) bucket, reused across requests forever — no dynamic
  shapes, no recompiles mid-stream.
* **Per-slot lengths**: every batch row carries its own ``length``;
  attention masks key positions ``>= length`` so right-padded prefills and
  continuously-batched decodes of different-length requests share one
  program (the continuous-batching primitive ``serve/decode.py`` builds
  on).
* **GQA-aware**: queries are grouped over KV heads
  (``(B, KV, G, D) x (B, C, KV, D)``) so grouped-query models never
  materialize repeated K/V — the cache stays at KV-head width, which is
  the whole point of GQA for decode bandwidth.
* **Decode is one fused dot per layer**: at ``S_q = 1`` attention is
  HBM-bandwidth-bound (read K/V once); a flash kernel cannot beat the
  plain masked dot XLA emits, so the Pallas path is reserved for prefill
  (``attention_impl="flash"`` with ``q_offset`` chunked prefill).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rotary import apply_rope, rope_frequencies

Cache = Dict[str, jax.Array]


def cache_bucket(n: int, minimum: int = 128) -> int:
    """Smallest power-of-two >= n (>= minimum): the shape buckets decode
    programs compile for."""
    c = minimum
    while c < n:
        c *= 2
    return c


def init_cache(config: LlamaConfig, batch: int, capacity: int,
               dtype=None) -> Cache:
    """Zeroed KV cache for ``batch`` slots of ``capacity`` tokens."""
    c = config
    if c.moe_experts:
        raise NotImplementedError(
            "KV-cache decode for MoE configs is not implemented yet "
            "(dense + GQA only)")
    dt = dtype or c.dtype
    shape = (c.n_layers, batch, capacity, c.n_kv_heads, c.head_dim)
    return {
        "k": jnp.zeros(shape, dt),
        "v": jnp.zeros(shape, dt),
        "length": jnp.zeros((batch,), jnp.int32),
    }


def _qkv(layer, h, config: LlamaConfig):
    c = config
    if "wqkv" in layer:
        qkv = jnp.einsum("bse,ehd->bshd", h, layer["wqkv"].astype(h.dtype))
        return (qkv[:, :, :c.n_heads],
                qkv[:, :, c.n_heads:c.n_heads + c.n_kv_heads],
                qkv[:, :, c.n_heads + c.n_kv_heads:])
    q = jnp.einsum("bse,ehd->bshd", h, layer["wq"].astype(h.dtype))
    k = jnp.einsum("bse,ehd->bshd", h, layer["wk"].astype(h.dtype))
    v = jnp.einsum("bse,ehd->bshd", h, layer["wv"].astype(h.dtype))
    return q, k, v


def _mlp(layer, x, config: LlamaConfig):
    h2 = rms_norm(x, layer["mlp_norm"], config.norm_eps)
    if "w_gate_up" in layer:
        gate_up = jnp.einsum("bse,em->bsm", h2,
                             layer["w_gate_up"].astype(h2.dtype))
        gate, up = jnp.split(gate_up, 2, axis=-1)
    else:
        gate = jnp.einsum("bse,em->bsm", h2,
                          layer["w_gate"].astype(h2.dtype))
        up = jnp.einsum("bse,em->bsm", h2, layer["w_up"].astype(h2.dtype))
    ffn = jax.nn.silu(gate) * up
    down = jnp.einsum("bsm,me->bse", ffn, layer["w_down"].astype(h2.dtype))
    return x + down


def prefill(params: Dict[str, Any], tokens: jax.Array, cache: Cache,
            config: LlamaConfig,
            lengths: Optional[jax.Array] = None
            ) -> Tuple[jax.Array, Cache]:
    """Process right-padded prompts (B, S), filling the cache.

    Returns ``(last_logits (B, V) fp32, cache)`` where ``last_logits`` is
    the next-token distribution at each row's final REAL token. Causality
    keeps real positions clean of the padding (padding sits to the right);
    the junk K/V the padded tail writes is masked by ``length`` at decode
    time. Prefill attention uses the config's impl ("flash" = the Pallas
    kernel with chunked ``q_offset``)."""
    from ray_tpu.models.llama import _decoder_layer

    c = config
    B, S = tokens.shape
    capacity = cache["k"].shape[2]
    if S > capacity:
        raise ValueError(f"prompt length {S} exceeds cache capacity "
                         f"{capacity}")
    if lengths is None:
        lengths = jnp.full((B,), S, jnp.int32)
    x = params["tok_embed"].astype(c.dtype)[tokens]
    cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)

    def body(x, layer):
        # Full-layer forward identical to training (shared code), but k/v
        # are recomputed here to feed the cache — cheap (two matmuls)
        # next to the layer itself, and keeps _decoder_layer signature
        # untouched for the train path.
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        _, k, v = _qkv(layer, h, c)
        k = apply_rope(k, cos, sin)
        x, _aux = _decoder_layer(c, x, layer, cos, sin, 0)
        return x, (k.astype(cache["k"].dtype), v.astype(cache["v"].dtype))

    x, (ks, vs) = jax.lax.scan(body, x, params["layers"])
    # ks: (L, B, S, KV, D) -> cache[:, :, :S]
    new_k = jax.lax.dynamic_update_slice(cache["k"], ks, (0, 0, 0, 0, 0))
    new_v = jax.lax.dynamic_update_slice(cache["v"], vs, (0, 0, 0, 0, 0))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    idx = jnp.clip(lengths - 1, 0, S - 1)
    x_last = jnp.take_along_axis(
        x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = jnp.einsum("be,ev->bv", x_last,
                        params["lm_head"].astype(c.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v, "length": lengths}


def prefill_suffix(params: Dict[str, Any], tokens: jax.Array,
                   cache: Cache, config: LlamaConfig,
                   prefix_lens: jax.Array, lengths: jax.Array
                   ) -> Tuple[jax.Array, Cache]:
    """Suffix-only prefill: process right-padded suffix ``tokens`` (B, S)
    starting at ``pos = prefix_lens`` against cache rows whose first
    ``prefix_lens`` positions are ALREADY populated (spliced from a
    prefix pool — the serve-plane prefix cache's other half).

    ``lengths`` is each row's TOTAL length (prefix + real suffix); the
    real suffix length is ``lengths - prefix_lens``. Shapes stay static
    (one program per (B, S) bucket pair); prefix offsets are traced, so
    the compiled program set does not grow with prefix lengths.

    Masking is exact for the spliced region: a suffix query at absolute
    position p attends key positions <= p — the cached prefix plus the
    causal part of the suffix. Stale positions beyond the written suffix
    are causally invisible here and masked by ``length`` at decode time.
    Suffix K/V scatters past the padded tail land out of bounds and are
    dropped by XLA (never clamped into live rows).

    Returns ``(last_logits (B, V) fp32, cache)`` with ``last_logits``
    taken at each row's final REAL token, exactly like ``prefill``."""
    c = config
    B, S = tokens.shape
    capacity = cache["k"].shape[2]
    cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)
    x = params["tok_embed"].astype(c.dtype)[tokens]        # (B, S, E)
    abs_pos = prefix_lens[:, None] + jnp.arange(S)[None, :]  # (B, S)
    kv_groups = c.n_heads // c.n_kv_heads
    scale = c.head_dim ** -0.5
    rows = jnp.arange(B)
    valid = (jnp.arange(capacity)[None, None, :]
             <= abs_pos[:, :, None])                        # (B, S, C)

    def body(x, inp):
        layer, k_c, v_c = inp                # k_c/v_c: (B, C, KV, D)
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        q, k_new, v_new = _qkv(layer, h, c)  # (B, S, H/KV, D)
        q = apply_rope(q, cos, sin, positions=abs_pos)
        k_new = apply_rope(k_new, cos, sin, positions=abs_pos)
        k_c = k_c.at[rows[:, None], abs_pos].set(k_new.astype(k_c.dtype))
        v_c = v_c.at[rows[:, None], abs_pos].set(v_new.astype(v_c.dtype))
        qg = q.reshape(B, S, c.n_kv_heads, kv_groups, c.head_dim)
        scores = jnp.einsum("bskgd,bckd->bkgsc", qg, k_c,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(valid[:, None, None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum("bkgsc,bckd->bkgsd", probs.astype(v_c.dtype), v_c)
        att = att.transpose(0, 3, 1, 2, 4).reshape(
            B, S, c.n_heads, c.head_dim).astype(x.dtype)
        out = jnp.einsum("bshd,hde->bse", att, layer["wo"].astype(x.dtype))
        x = x + out
        x = _mlp(layer, x, c)
        return x, (k_c, v_c)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    idx = jnp.clip(lengths - prefix_lens - 1, 0, S - 1)
    x_last = jnp.take_along_axis(
        x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = jnp.einsum("be,ev->bv", x_last,
                        params["lm_head"].astype(c.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v, "length": lengths}


def decode_step(params: Dict[str, Any], cache: Cache, tokens: jax.Array,
                config: LlamaConfig) -> Tuple[jax.Array, Cache]:
    """Append one token per slot and return next-token logits.

    ``tokens``: (B,) int32 — each row's token is written at position
    ``cache["length"][row]``; attention sees positions ``<= length``.
    Jit with ``donate_argnums`` on the cache: the update is in-place on
    device (no (L,B,C,KV,D) copy per token)."""
    c = config
    B = tokens.shape[0]
    pos = cache["length"]  # (B,)
    cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)
    x = params["tok_embed"].astype(c.dtype)[tokens][:, None]  # (B, 1, E)
    capacity = cache["k"].shape[2]
    kv_groups = c.n_heads // c.n_kv_heads
    scale = c.head_dim ** -0.5
    rows = jnp.arange(B)
    # Key positions 0..pos are valid (including the token being appended).
    valid = (jnp.arange(capacity)[None, :] <= pos[:, None])  # (B, C)

    def body(x, inp):
        layer, k_c, v_c = inp
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        q, k_new, v_new = _qkv(layer, h, c)      # (B, 1, H/KV, D)
        q = apply_rope(q, cos, sin, positions=pos[:, None])
        k_new = apply_rope(k_new, cos, sin, positions=pos[:, None])
        k_c = k_c.at[rows, pos].set(k_new[:, 0].astype(k_c.dtype))
        v_c = v_c.at[rows, pos].set(v_new[:, 0].astype(v_c.dtype))
        # GQA attention against the cache at KV-head width: q grouped as
        # (B, KV, G, D), scores (B, KV, G, C) — repeated K/V never exist.
        qg = q[:, 0].reshape(B, c.n_kv_heads, kv_groups, c.head_dim)
        scores = jnp.einsum("bkgd,bckd->bkgc", qg, k_c,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(valid[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum("bkgc,bckd->bkgd", probs.astype(v_c.dtype), v_c)
        att = att.reshape(B, 1, c.n_heads, c.head_dim).astype(x.dtype)
        out = jnp.einsum("bshd,hde->bse", att, layer["wo"].astype(x.dtype))
        x = x + out
        x = _mlp(layer, x, c)
        return x, (k_c, v_c)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    logits = jnp.einsum("be,ev->bv", x[:, 0],
                        params["lm_head"].astype(c.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v, "length": pos + 1}


def decode_chunk(params: Dict[str, Any], cache: Cache, tokens: jax.Array,
                 config: LlamaConfig, k: int
                 ) -> Tuple[jax.Array, Cache]:
    """``k`` greedy decode steps in ONE jitted program (lax.scan): each
    step's argmax feeds the next. Returns (tokens (k, B), cache).

    This is the dispatch-amortization lever for serving: one device call
    per K tokens instead of per token — on dispatch-floor-bound rigs
    (tunneled chips, small models) it multiplies decode throughput by
    ~K. The continuous batcher uses it between admission points (greedy
    requests only; sampling stays per-step)."""
    def body(carry, _):
        cache, tok = carry
        logits, cache = decode_step(params, cache, tok, config)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (cache, nxt), nxt

    (cache, _), toks = jax.lax.scan(body, (cache, tokens), None, length=k)
    return toks, cache


def _sample(logits: jax.Array, temperature: float, key) -> jax.Array:
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, logits / temperature, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("config", "max_new_tokens",
                                   "temperature", "eos_id"))
def _generate_jit(params, tokens, lengths, key, config: LlamaConfig,
                  max_new_tokens: int, temperature: float,
                  eos_id: int):
    B, S = tokens.shape
    capacity = cache_bucket(S + max_new_tokens)
    cache = init_cache(config, B, capacity)
    logits, cache = prefill(params, tokens, cache, config, lengths)
    key, sub = jax.random.split(key)
    first = _sample(logits, temperature, sub)
    done0 = (first == eos_id) if eos_id >= 0 else jnp.zeros(B, bool)

    def step(carry, _):
        cache, tok, key, done = carry
        logits, cache = decode_step(params, cache, tok, config)
        key, sub = jax.random.split(key)
        nxt = _sample(logits, temperature, sub)
        nxt = jnp.where(done, eos_id if eos_id >= 0 else 0, nxt)
        done = done | ((nxt == eos_id) if eos_id >= 0 else False)
        return (cache, nxt, key, done), nxt

    (_, _, _, _), rest = jax.lax.scan(
        step, (cache, first, key, done0), None,
        length=max_new_tokens - 1)
    return jnp.concatenate([first[None], rest], axis=0).T  # (B, max_new)


def generate(params: Dict[str, Any], tokens, config: LlamaConfig,
             max_new_tokens: int = 32, temperature: float = 0.0,
             key=None, eos_id: Optional[int] = None,
             lengths=None) -> jax.Array:
    """Generate ``max_new_tokens`` per prompt row as ONE jitted program
    (prefill + scanned decode): the benchmark/offline path. Serving uses
    ``prefill``/``decode_step`` directly through ``serve/decode.py`` so
    requests can join/leave the batch between steps."""
    tokens = jnp.asarray(tokens, jnp.int32)
    if tokens.ndim == 1:
        tokens = tokens[None]
    if key is None:
        key = jax.random.key(0)
    if lengths is not None:
        lengths = jnp.asarray(lengths, jnp.int32)
    return _generate_jit(params, tokens, lengths, key, config,
                         int(max_new_tokens), float(temperature),
                         -1 if eos_id is None else int(eos_id))
