"""Autoregressive decoding for the Llama family: KV cache + jitted
prefill/decode steps + ``generate``.

The serving-side other half of ``models/llama.py`` (VERDICT r4 Missing #2;
reference: serving generation flows through the model-agnostic replica call
path ``python/ray/serve/_private/replica.py:231`` with streaming
``proxy.py:761`` — the reference has no model library, so its KV cache
lives in user code/vLLM; here it is TPU-native and first-class).

Design for the XLA/TPU execution model:

* **Static cache buckets**: the cache is a fixed ``(L, B, C, KV, D)``
  allocation (``C`` = a power-of-two-ish capacity bucket). One compiled
  program per (B, C) bucket, reused across requests forever — no dynamic
  shapes, no recompiles mid-stream.
* **Per-slot lengths**: every batch row carries its own ``length``;
  attention masks key positions ``>= length`` so right-padded prefills and
  continuously-batched decodes of different-length requests share one
  program (the continuous-batching primitive ``serve/decode.py`` builds
  on).
* **GQA-aware**: queries are grouped over KV heads
  (``(B, KV, G, D) x (B, C, KV, D)``) so grouped-query models never
  materialize repeated K/V — the cache stays at KV-head width, which is
  the whole point of GQA for decode bandwidth.
* **Decode is one fused dot per layer**: at ``S_q = 1`` attention is
  HBM-bandwidth-bound (read K/V once); a flash kernel cannot beat the
  plain masked dot XLA emits, so the Pallas path is reserved for prefill
  (``attention_impl="flash"`` with ``q_offset`` chunked prefill).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rotary import apply_rope, rope_frequencies
from ray_tpu.parallel.sharding import constrain

Cache = Dict[str, jax.Array]


def cache_bucket(n: int, minimum: int = 128) -> int:
    """Smallest power-of-two >= n (>= minimum): the shape buckets decode
    programs compile for."""
    c = minimum
    while c < n:
        c *= 2
    return c


def init_cache(config: LlamaConfig, batch: int, capacity: int,
               dtype=None) -> Cache:
    """Zeroed KV cache for ``batch`` slots of ``capacity`` tokens."""
    c = config
    if c.moe_experts:
        raise NotImplementedError(
            "KV-cache decode for MoE configs is not implemented yet "
            "(dense + GQA only)")
    dt = dtype or c.dtype
    shape = (c.n_layers, batch, capacity, c.n_kv_heads, c.head_dim)
    return {
        "k": jnp.zeros(shape, dt),
        "v": jnp.zeros(shape, dt),
        "length": jnp.zeros((batch,), jnp.int32),
    }


def _qkv(layer, h, config: LlamaConfig):
    c = config
    if "wqkv" in layer:
        qkv = jnp.einsum("bse,ehd->bshd", h, layer["wqkv"].astype(h.dtype))
        return (qkv[:, :, :c.n_heads],
                qkv[:, :, c.n_heads:c.n_heads + c.n_kv_heads],
                qkv[:, :, c.n_heads + c.n_kv_heads:])
    q = jnp.einsum("bse,ehd->bshd", h, layer["wq"].astype(h.dtype))
    k = jnp.einsum("bse,ehd->bshd", h, layer["wk"].astype(h.dtype))
    v = jnp.einsum("bse,ehd->bshd", h, layer["wv"].astype(h.dtype))
    return q, k, v


def _mlp(layer, x, config: LlamaConfig):
    h2 = rms_norm(x, layer["mlp_norm"], config.norm_eps)
    if "w_gate_up" in layer:
        gate_up = jnp.einsum("bse,em->bsm", h2,
                             layer["w_gate_up"].astype(h2.dtype))
        gate, up = jnp.split(gate_up, 2, axis=-1)
    else:
        gate = jnp.einsum("bse,em->bsm", h2,
                          layer["w_gate"].astype(h2.dtype))
        up = jnp.einsum("bse,em->bsm", h2, layer["w_up"].astype(h2.dtype))
    ffn = jax.nn.silu(gate) * up
    # Pre-contraction anchor (see llama._decoder_layer): under DECODE
    # rules this all-gathers the mlp-sharded hidden so the w_down
    # reduction is never split across the mesh (bit-exactness contract).
    ffn = constrain(ffn, ("batch", "length", "mlp_hidden"))
    down = jnp.einsum("bsm,me->bse", ffn, layer["w_down"].astype(h2.dtype))
    return x + down


def prefill(params: Dict[str, Any], tokens: jax.Array, cache: Cache,
            config: LlamaConfig,
            lengths: Optional[jax.Array] = None
            ) -> Tuple[jax.Array, Cache]:
    """Process right-padded prompts (B, S), filling the cache.

    Returns ``(last_logits (B, V) fp32, cache)`` where ``last_logits`` is
    the next-token distribution at each row's final REAL token. Causality
    keeps real positions clean of the padding (padding sits to the right);
    the junk K/V the padded tail writes is masked by ``length`` at decode
    time. Prefill attention uses the config's impl ("flash" = the Pallas
    kernel with chunked ``q_offset``)."""
    from ray_tpu.models.llama import _decoder_layer

    c = config
    B, S = tokens.shape
    capacity = cache["k"].shape[2]
    if S > capacity:
        raise ValueError(f"prompt length {S} exceeds cache capacity "
                         f"{capacity}")
    if lengths is None:
        lengths = jnp.full((B,), S, jnp.int32)
    x = params["tok_embed"].astype(c.dtype)[tokens]
    cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)

    def body(x, layer):
        # Full-layer forward identical to training (shared code), but k/v
        # are recomputed here to feed the cache — cheap (two matmuls)
        # next to the layer itself, and keeps _decoder_layer signature
        # untouched for the train path.
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        _, k, v = _qkv(layer, h, c)
        k = apply_rope(k, cos, sin)
        k = constrain(k, ("batch", "length", "kv_heads", "head_dim"))
        v = constrain(v, ("batch", "length", "kv_heads", "head_dim"))
        x, _aux = _decoder_layer(c, x, layer, cos, sin, 0)
        return x, (k.astype(cache["k"].dtype), v.astype(cache["v"].dtype))

    x, (ks, vs) = jax.lax.scan(body, x, params["layers"])
    # ks: (L, B, S, KV, D) -> cache[:, :, :S]
    new_k = jax.lax.dynamic_update_slice(cache["k"], ks, (0, 0, 0, 0, 0))
    new_v = jax.lax.dynamic_update_slice(cache["v"], vs, (0, 0, 0, 0, 0))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    idx = jnp.clip(lengths - 1, 0, S - 1)
    x_last = jnp.take_along_axis(
        x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = jnp.einsum("be,ev->bv", x_last,
                        params["lm_head"].astype(c.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v, "length": lengths}


def prefill_suffix(params: Dict[str, Any], tokens: jax.Array,
                   cache: Cache, config: LlamaConfig,
                   prefix_lens: jax.Array, lengths: jax.Array
                   ) -> Tuple[jax.Array, Cache]:
    """Suffix-only prefill: process right-padded suffix ``tokens`` (B, S)
    starting at ``pos = prefix_lens`` against cache rows whose first
    ``prefix_lens`` positions are ALREADY populated (spliced from a
    prefix pool — the serve-plane prefix cache's other half).

    ``lengths`` is each row's TOTAL length (prefix + real suffix); the
    real suffix length is ``lengths - prefix_lens``. Shapes stay static
    (one program per (B, S) bucket pair); prefix offsets are traced, so
    the compiled program set does not grow with prefix lengths.

    Masking is exact for the spliced region: a suffix query at absolute
    position p attends key positions <= p — the cached prefix plus the
    causal part of the suffix. Stale positions beyond the written suffix
    are causally invisible here and masked by ``length`` at decode time.
    Suffix K/V scatters past the padded tail land out of bounds and are
    dropped by XLA (never clamped into live rows).

    Returns ``(last_logits (B, V) fp32, cache)`` with ``last_logits``
    taken at each row's final REAL token, exactly like ``prefill``."""
    c = config
    B, S = tokens.shape
    capacity = cache["k"].shape[2]
    cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)
    x = params["tok_embed"].astype(c.dtype)[tokens]        # (B, S, E)
    abs_pos = prefix_lens[:, None] + jnp.arange(S)[None, :]  # (B, S)
    kv_groups = c.n_heads // c.n_kv_heads
    scale = c.head_dim ** -0.5
    rows = jnp.arange(B)
    valid = (jnp.arange(capacity)[None, None, :]
             <= abs_pos[:, :, None])                        # (B, S, C)

    def body(x, inp):
        layer, k_c, v_c = inp                # k_c/v_c: (B, C, KV, D)
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        q, k_new, v_new = _qkv(layer, h, c)  # (B, S, H/KV, D)
        q = apply_rope(q, cos, sin, positions=abs_pos)
        k_new = apply_rope(k_new, cos, sin, positions=abs_pos)
        q = constrain(q, ("batch", "length", "heads", "head_dim"))
        k_new = constrain(k_new,
                          ("batch", "length", "kv_heads", "head_dim"))
        v_new = constrain(v_new,
                          ("batch", "length", "kv_heads", "head_dim"))
        k_c = k_c.at[rows[:, None], abs_pos].set(k_new.astype(k_c.dtype))
        v_c = v_c.at[rows[:, None], abs_pos].set(v_new.astype(v_c.dtype))
        qg = q.reshape(B, S, c.n_kv_heads, kv_groups, c.head_dim)
        scores = jnp.einsum("bskgd,bckd->bkgsc", qg, k_c,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(valid[:, None, None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum("bkgsc,bckd->bkgsd", probs.astype(v_c.dtype), v_c)
        att = att.transpose(0, 3, 1, 2, 4).reshape(
            B, S, c.n_heads, c.head_dim).astype(x.dtype)
        att = constrain(att, ("batch", "length", "attn_heads", "head_dim"))
        out = jnp.einsum("bshd,hde->bse", att, layer["wo"].astype(x.dtype))
        x = x + out
        x = _mlp(layer, x, c)
        return x, (k_c, v_c)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    idx = jnp.clip(lengths - prefix_lens - 1, 0, S - 1)
    x_last = jnp.take_along_axis(
        x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = jnp.einsum("be,ev->bv", x_last,
                        params["lm_head"].astype(c.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v, "length": lengths}


def decode_step(params: Dict[str, Any], cache: Cache, tokens: jax.Array,
                config: LlamaConfig) -> Tuple[jax.Array, Cache]:
    """Append one token per slot and return next-token logits.

    ``tokens``: (B,) int32 — each row's token is written at position
    ``cache["length"][row]``; attention sees positions ``<= length``.
    Jit with ``donate_argnums`` on the cache: the update is in-place on
    device (no (L,B,C,KV,D) copy per token)."""
    c = config
    B = tokens.shape[0]
    pos = cache["length"]  # (B,)
    cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)
    x = params["tok_embed"].astype(c.dtype)[tokens][:, None]  # (B, 1, E)
    capacity = cache["k"].shape[2]
    kv_groups = c.n_heads // c.n_kv_heads
    scale = c.head_dim ** -0.5
    rows = jnp.arange(B)
    # Key positions 0..pos are valid (including the token being appended).
    valid = (jnp.arange(capacity)[None, :] <= pos[:, None])  # (B, C)

    def body(x, inp):
        layer, k_c, v_c = inp
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        q, k_new, v_new = _qkv(layer, h, c)      # (B, 1, H/KV, D)
        q = apply_rope(q, cos, sin, positions=pos[:, None])
        k_new = apply_rope(k_new, cos, sin, positions=pos[:, None])
        q = constrain(q, ("batch", "length", "heads", "head_dim"))
        k_new = constrain(k_new,
                          ("batch", "length", "kv_heads", "head_dim"))
        v_new = constrain(v_new,
                          ("batch", "length", "kv_heads", "head_dim"))
        k_c = k_c.at[rows, pos].set(k_new[:, 0].astype(k_c.dtype))
        v_c = v_c.at[rows, pos].set(v_new[:, 0].astype(v_c.dtype))
        # GQA attention against the cache at KV-head width: q grouped as
        # (B, KV, G, D), scores (B, KV, G, C) — repeated K/V never exist.
        qg = q[:, 0].reshape(B, c.n_kv_heads, kv_groups, c.head_dim)
        scores = jnp.einsum("bkgd,bckd->bkgc", qg, k_c,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(valid[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum("bkgc,bckd->bkgd", probs.astype(v_c.dtype), v_c)
        att = att.reshape(B, 1, c.n_heads, c.head_dim).astype(x.dtype)
        att = constrain(att, ("batch", "length", "attn_heads", "head_dim"))
        out = jnp.einsum("bshd,hde->bse", att, layer["wo"].astype(x.dtype))
        x = x + out
        x = _mlp(layer, x, c)
        return x, (k_c, v_c)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    logits = jnp.einsum("be,ev->bv", x[:, 0],
                        params["lm_head"].astype(c.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v, "length": pos + 1}


def decode_chunk(params: Dict[str, Any], cache: Cache, tokens: jax.Array,
                 config: LlamaConfig, k: int
                 ) -> Tuple[jax.Array, Cache]:
    """``k`` greedy decode steps in ONE jitted program (lax.scan): each
    step's argmax feeds the next. Returns (tokens (k, B), cache).

    This is the dispatch-amortization lever for serving: one device call
    per K tokens instead of per token — on dispatch-floor-bound rigs
    (tunneled chips, small models) it multiplies decode throughput by
    ~K. The continuous batcher uses it between admission points (greedy
    requests only; sampling stays per-step)."""
    def body(carry, _):
        cache, tok = carry
        logits, cache = decode_step(params, cache, tok, config)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (cache, nxt), nxt

    (cache, _), toks = jax.lax.scan(body, (cache, tokens), None, length=k)
    return toks, cache


# --------------------------------------------------------------- paged KV
#
# vLLM-style paged attention on XLA-friendly static shapes: K/V for ALL
# slots live in one device pool of ``(pages, page_tokens)`` blocks, and a
# per-slot block table (int32 page ids, static width) maps logical token
# positions to pool pages. Attention gathers a slot's pages back into
# logical order — the gathered layout is value-for-value identical to the
# contiguous cache, so the masked-dot attention below is BIT-EXACT vs the
# monolithic path (same values, same reduction order, same masks).
#
# Page id 0 is a reserved scratch page: block-table entries for positions
# a slot never allocated point at it, so pad writes land somewhere
# harmless (never read — masking is by per-slot ``length``/causality,
# exactly like the contiguous path). The host-side allocator
# (``serve/paging.py``) hands out ids 1..pages.


def init_page_pool(config: LlamaConfig, pages: int, page_tokens: int,
                   dtype=None) -> Cache:
    """Zeroed paged KV pool: ``pages`` usable pages of ``page_tokens``
    tokens each, plus the reserved scratch page 0 (so the arrays hold
    ``pages + 1`` page rows)."""
    c = config
    if c.moe_experts:
        raise NotImplementedError(
            "paged KV-cache decode for MoE configs is not implemented yet "
            "(dense + GQA only)")
    dt = dtype or c.dtype
    shape = (c.n_layers, pages + 1, page_tokens, c.n_kv_heads, c.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def paged_prefill(params: Dict[str, Any], tokens: jax.Array, pool: Cache,
                  block_tables: jax.Array, config: LlamaConfig,
                  lengths: Optional[jax.Array] = None
                  ) -> Tuple[jax.Array, Cache]:
    """Full prefill of right-padded prompts (B, S) scattered into pool
    pages. The attention itself is the plain causal ``prefill`` (a fresh
    prompt attends only to itself — no pool reads), so logits are
    bit-exact vs the contiguous path; only the K/V destination differs:
    position ``p`` of row ``b`` lands in page
    ``block_tables[b, p // T]`` at offset ``p % T``.

    ``block_tables``: (B, W) int32 with ``W * T >= S``. Pad positions
    past a row's real length scatter into whatever page backs them —
    the row's own tail page or the scratch page 0 — and are never read
    (causally invisible at prefill, masked by ``length`` at decode)."""
    B, S = tokens.shape
    T = pool["k"].shape[2]
    scratch = {
        "k": jnp.zeros(pool["k"].shape[:1] + (B, S)
                       + pool["k"].shape[3:], pool["k"].dtype),
        "v": jnp.zeros(pool["v"].shape[:1] + (B, S)
                       + pool["v"].shape[3:], pool["v"].dtype),
        "length": jnp.zeros((B,), jnp.int32),
    }
    logits, scratch = prefill(params, tokens, scratch, config, lengths)
    pos = jnp.arange(S)
    pages = block_tables[:, pos // T]                    # (B, S)
    offs = jnp.broadcast_to(pos % T, (B, S))
    new_k = pool["k"].at[:, pages, offs].set(scratch["k"])
    new_v = pool["v"].at[:, pages, offs].set(scratch["v"])
    return logits, {"k": new_k, "v": new_v}


def paged_prefill_suffix(params: Dict[str, Any], tokens: jax.Array,
                         pool: Cache, block_tables: jax.Array,
                         config: LlamaConfig, prefix_lens: jax.Array,
                         lengths: jax.Array) -> Tuple[jax.Array, Cache]:
    """Suffix prefill against paged context: process right-padded suffix
    ``tokens`` (B, S) from ``pos = prefix_lens``, attending to the pages
    ``block_tables`` (B, W) maps — the shared/previously-filled prefix
    pages plus the causal part of the suffix. This one program is the
    prefix-hit splice (prefix pages borrowed from the pool with ZERO
    copies — the block table entries ARE the splice) and the chunked-
    prefill continuation step (prefix = what earlier chunks wrote).

    ``W`` is a static page width covering ``prefix + suffix`` for the
    whole wave; pass block tables sliced to it so gather/attention cost
    scales with what the wave touches, not the engine's max context.
    Suffix K/V additionally scatters into the pool at the absolute
    positions (always pages owned exclusively by the row: sharing is
    full-page and writes start past the shared region)."""
    c = config
    B, S = tokens.shape
    T = pool["k"].shape[2]
    W = block_tables.shape[1]
    C = W * T
    cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)
    x = params["tok_embed"].astype(c.dtype)[tokens]          # (B, S, E)
    abs_pos = prefix_lens[:, None] + jnp.arange(S)[None, :]  # (B, S)
    kv_groups = c.n_heads // c.n_kv_heads
    scale = c.head_dim ** -0.5
    rows = jnp.arange(B)
    # Pad positions past the static page window (a bucket overhanging a
    # row's real length) scatter to the SCRATCH page, never a clamped
    # real page — an index clamp here would corrupt live K/V at the
    # pad's page offset.
    pages = jnp.where(
        abs_pos < C,
        block_tables[rows[:, None], jnp.minimum(abs_pos // T, W - 1)], 0)
    offs = abs_pos % T
    valid = (jnp.arange(C)[None, None, :]
             <= abs_pos[:, :, None])                         # (B, S, C)

    def body(x, inp):
        layer, k_p, v_p = inp               # pool slices (P+1, T, KV, D)
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        q, k_new, v_new = _qkv(layer, h, c)  # (B, S, H/KV, D)
        q = apply_rope(q, cos, sin, positions=abs_pos)
        k_new = apply_rope(k_new, cos, sin, positions=abs_pos)
        q = constrain(q, ("batch", "length", "heads", "head_dim"))
        k_new = constrain(k_new,
                          ("batch", "length", "kv_heads", "head_dim"))
        v_new = constrain(v_new,
                          ("batch", "length", "kv_heads", "head_dim"))
        k_p = k_p.at[pages, offs].set(k_new.astype(k_p.dtype))
        v_p = v_p.at[pages, offs].set(v_new.astype(v_p.dtype))
        # Gather AFTER the scatter so the suffix's own causal K/V is in
        # view; layout is logical position order, like the contiguous
        # rows, so attention below is the exact prefill_suffix math.
        k_c = k_p[block_tables].reshape(B, C, c.n_kv_heads, c.head_dim)
        v_c = v_p[block_tables].reshape(B, C, c.n_kv_heads, c.head_dim)
        qg = q.reshape(B, S, c.n_kv_heads, kv_groups, c.head_dim)
        scores = jnp.einsum("bskgd,bckd->bkgsc", qg, k_c,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(valid[:, None, None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum("bkgsc,bckd->bkgsd", probs.astype(v_c.dtype), v_c)
        att = att.transpose(0, 3, 1, 2, 4).reshape(
            B, S, c.n_heads, c.head_dim).astype(x.dtype)
        att = constrain(att, ("batch", "length", "attn_heads", "head_dim"))
        out = jnp.einsum("bshd,hde->bse", att, layer["wo"].astype(x.dtype))
        x = x + out
        x = _mlp(layer, x, c)
        return x, (k_p, v_p)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], pool["k"], pool["v"]))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    idx = jnp.clip(lengths - prefix_lens - 1, 0, S - 1)
    x_last = jnp.take_along_axis(
        x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = jnp.einsum("be,ev->bv", x_last,
                        params["lm_head"].astype(c.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def paged_decode_step(params: Dict[str, Any], pool: Cache,
                      block_tables: jax.Array, lengths: jax.Array,
                      tokens: jax.Array, config: LlamaConfig
                      ) -> Tuple[jax.Array, Cache, jax.Array]:
    """One decode token per slot against paged context. ``tokens``: (B,)
    int32 written at position ``lengths[b]`` of each row's block-mapped
    sequence; attention sees positions ``<= length`` across the row's
    gathered pages — value-for-value the contiguous ``decode_step``."""
    c = config
    B = tokens.shape[0]
    T = pool["k"].shape[2]
    W = block_tables.shape[1]
    C = W * T
    pos = lengths                                            # (B,)
    cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)
    x = params["tok_embed"].astype(c.dtype)[tokens][:, None]  # (B, 1, E)
    kv_groups = c.n_heads // c.n_kv_heads
    scale = c.head_dim ** -0.5
    rows = jnp.arange(B)
    # Idle/mid-prefill slots also flow through this program (static B);
    # their parked cursor can sit past the page window — route those
    # writes to the scratch page instead of clamping into a live page.
    page = jnp.where(pos < C,
                     block_tables[rows, jnp.minimum(pos // T, W - 1)], 0)
    off = pos % T
    valid = (jnp.arange(C)[None, :] <= pos[:, None])         # (B, C)

    def body(x, inp):
        layer, k_p, v_p = inp
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        q, k_new, v_new = _qkv(layer, h, c)       # (B, 1, H/KV, D)
        q = apply_rope(q, cos, sin, positions=pos[:, None])
        k_new = apply_rope(k_new, cos, sin, positions=pos[:, None])
        q = constrain(q, ("batch", "length", "heads", "head_dim"))
        k_new = constrain(k_new,
                          ("batch", "length", "kv_heads", "head_dim"))
        v_new = constrain(v_new,
                          ("batch", "length", "kv_heads", "head_dim"))
        k_p = k_p.at[page, off].set(k_new[:, 0].astype(k_p.dtype))
        v_p = v_p.at[page, off].set(v_new[:, 0].astype(v_p.dtype))
        k_c = k_p[block_tables].reshape(B, C, c.n_kv_heads, c.head_dim)
        v_c = v_p[block_tables].reshape(B, C, c.n_kv_heads, c.head_dim)
        qg = q[:, 0].reshape(B, c.n_kv_heads, kv_groups, c.head_dim)
        scores = jnp.einsum("bkgd,bckd->bkgc", qg, k_c,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(valid[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum("bkgc,bckd->bkgd", probs.astype(v_c.dtype), v_c)
        att = att.reshape(B, 1, c.n_heads, c.head_dim).astype(x.dtype)
        att = constrain(att, ("batch", "length", "attn_heads", "head_dim"))
        out = jnp.einsum("bshd,hde->bse", att, layer["wo"].astype(x.dtype))
        x = x + out
        x = _mlp(layer, x, c)
        return x, (k_p, v_p)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], pool["k"], pool["v"]))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    logits = jnp.einsum("be,ev->bv", x[:, 0],
                        params["lm_head"].astype(c.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v}, pos + 1


def paged_decode_chunk(params: Dict[str, Any], pool: Cache,
                       block_tables: jax.Array, lengths: jax.Array,
                       tokens: jax.Array, config: LlamaConfig, k: int
                       ) -> Tuple[jax.Array, Cache, jax.Array]:
    """``k`` greedy paged decode steps in ONE jitted program (the
    dispatch-amortization lever, paged flavor). The block tables are
    static across the chunk: the caller must have pages allocated to
    cover ``length + k`` for every stepping slot."""
    def body(carry, _):
        pool, lens, tok = carry
        logits, pool, lens = paged_decode_step(params, pool, block_tables,
                                               lens, tok, config)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (pool, lens, nxt), nxt

    (pool, lengths, _), toks = jax.lax.scan(
        body, (pool, lengths, tokens), None, length=k)
    return toks, pool, lengths


# --------------------------------------------- speculative decoding
#
# Two programs on top of the paged machinery: ``paged_verify`` scores a
# (k+1)-token suffix per row in ONE target forward (the ragged-position
# scatter/gather of ``paged_prefill_suffix``, but emitting logits at
# EVERY query position instead of the last real one — the per-position
# argmaxes are what the engine compares draft proposals against), and
# ``paged_spec_draft`` runs the small draft model: ingest up to two
# catch-up tokens (the tokens the target accepted since the draft's
# last committed position — bounded at 2 by the acceptance protocol),
# then greedily propose ``k`` tokens via a scanned decode. Greedy
# acceptance of the longest matching prefix makes spec-mode output
# provably identical to sequential greedy decode: position ``j``'s
# verify logits condition on exactly the tokens sequential decode would
# have conditioned on whenever proposals ``1..j`` were accepted.


def paged_verify(params: Dict[str, Any], tokens: jax.Array, pool: Cache,
                 block_tables: jax.Array, config: LlamaConfig,
                 prefix_lens: jax.Array) -> Tuple[jax.Array, Cache]:
    """Target-model verify forward: process right-padded rows ``tokens``
    (B, S = spec_k + 1) from ``pos = prefix_lens`` against the paged
    context and return logits at ALL ``S`` positions, shape (B, S, V).
    Row layout is ``[last_emitted, draft_1, .., draft_k]``; K/V for
    every position scatters into the row's pages (positions past the
    page window go to the scratch page), so the accepted prefix is
    committed by the same program that scores it — rejected tails are
    plain junk past the rolled-back ``length`` cursor, masked exactly
    like pad writes and overwritten by the next round's scatter before
    any gather can see them."""
    c = config
    B, S = tokens.shape
    T = pool["k"].shape[2]
    W = block_tables.shape[1]
    C = W * T
    cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)
    x = params["tok_embed"].astype(c.dtype)[tokens]          # (B, S, E)
    abs_pos = prefix_lens[:, None] + jnp.arange(S)[None, :]  # (B, S)
    kv_groups = c.n_heads // c.n_kv_heads
    scale = c.head_dim ** -0.5
    rows = jnp.arange(B)
    pages = jnp.where(
        abs_pos < C,
        block_tables[rows[:, None], jnp.minimum(abs_pos // T, W - 1)], 0)
    offs = abs_pos % T
    valid = (jnp.arange(C)[None, None, :]
             <= abs_pos[:, :, None])                         # (B, S, C)

    def body(x, inp):
        layer, k_p, v_p = inp               # pool slices (P+1, T, KV, D)
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        q, k_new, v_new = _qkv(layer, h, c)  # (B, S, H/KV, D)
        q = apply_rope(q, cos, sin, positions=abs_pos)
        k_new = apply_rope(k_new, cos, sin, positions=abs_pos)
        q = constrain(q, ("batch", "length", "heads", "head_dim"))
        k_new = constrain(k_new,
                          ("batch", "length", "kv_heads", "head_dim"))
        v_new = constrain(v_new,
                          ("batch", "length", "kv_heads", "head_dim"))
        k_p = k_p.at[pages, offs].set(k_new.astype(k_p.dtype))
        v_p = v_p.at[pages, offs].set(v_new.astype(v_p.dtype))
        k_c = k_p[block_tables].reshape(B, C, c.n_kv_heads, c.head_dim)
        v_c = v_p[block_tables].reshape(B, C, c.n_kv_heads, c.head_dim)
        qg = q.reshape(B, S, c.n_kv_heads, kv_groups, c.head_dim)
        scores = jnp.einsum("bskgd,bckd->bkgsc", qg, k_c,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(valid[:, None, None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum("bkgsc,bckd->bkgsd", probs.astype(v_c.dtype), v_c)
        att = att.transpose(0, 3, 1, 2, 4).reshape(
            B, S, c.n_heads, c.head_dim).astype(x.dtype)
        att = constrain(att, ("batch", "length", "attn_heads", "head_dim"))
        out = jnp.einsum("bshd,hde->bse", att, layer["wo"].astype(x.dtype))
        x = x + out
        x = _mlp(layer, x, c)
        return x, (k_p, v_p)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], pool["k"], pool["v"]))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    logits = jnp.einsum("bse,ev->bsv", x,
                        params["lm_head"].astype(c.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def paged_spec_draft(params: Dict[str, Any], pool: Cache,
                     block_tables: jax.Array, lengths: jax.Array,
                     catchup: jax.Array, catchup_lens: jax.Array,
                     config: LlamaConfig, k: int
                     ) -> Tuple[jax.Array, Cache]:
    """Draft-model propose step: ingest the ragged ``catchup`` rows
    (B, 2) — the true tokens the draft has not yet committed, 1 normally
    or 2 after a fully-accepted round — writing their K/V at positions
    ``lengths..lengths+catchup_lens-1``, then greedily roll ``k``
    proposals. Returns ``(proposals (B, k) int32, pool)``. The caller
    owns the draft ``length`` cursors (host-side rollback after
    acceptance); pages must cover ``lengths + catchup_lens + k - 1``
    positions. A 1-long catch-up row's pad slot writes junk one past
    the real token — the first proposal's decode step rewrites that
    exact position before anything gathers it."""
    logits, pool = paged_verify(params, catchup, pool, block_tables,
                                config, lengths)
    last = jnp.take_along_axis(
        logits, (catchup_lens - 1)[:, None, None].astype(jnp.int32),
        axis=1)[:, 0]                                        # (B, V)
    tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
    lens = lengths + catchup_lens

    def body(carry, _):
        pool, lens, tok = carry
        logits, pool, lens = paged_decode_step(params, pool, block_tables,
                                               lens, tok, config)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (pool, lens, nxt), nxt

    (pool, _, _), rest = jax.lax.scan(
        body, (pool, lens, tok), None, length=k - 1)
    toks = jnp.concatenate([tok[None], rest], axis=0).T      # (B, k)
    return toks, pool


# ------------------------------------------------- GSPMD serving (mesh)
#
# One replica spanning a pod (sub-)slice instead of one chip: weights,
# KV state and activations carry NamedShardings over the named 2-D
# ``decode_mesh`` (("batch", "model")) and every program above is jitted
# with in/out shardings — XLA inserts the collectives (no hand-rolled
# ring/all-reduce anywhere in the serve plane). The sharding rules
# (``parallel.sharding.DECODE_RULES``) never partition a contraction
# dim, so sharded logits are BIT-EXACT vs the single-chip programs:
# model size scales with the "model" axis (HBM per chip drops), slot
# count with the "batch" axis, and correctness is byte-identical.


def decode_shardings(config: LlamaConfig, mesh) -> Dict[str, Any]:
    """Sharding bundle for a decode replica on ``mesh`` (a
    ``parallel.mesh.decode_mesh``): NamedShardings for the params pytree,
    the contiguous KV cache, the paged pool, the contiguous prefix pool,
    and host-facing (replicated) outputs, plus the resolved rule table.

    ``cache["length"]`` stays replicated: it is a few bytes, every
    decode step scatters it at a traced slot index, and the host reads
    it back for admission accounting."""
    from jax.sharding import NamedSharding, PartitionSpec
    from ray_tpu.models.llama import decode_param_axes
    from ray_tpu.parallel.sharding import (decode_rules, spec_for,
                                           tree_shardings)

    rules = decode_rules(config, mesh)

    def ns(*axes):
        return NamedSharding(mesh, spec_for(axes, rules))

    kv_row = ("layers", "batch", None, "kv_heads", "head_dim")
    pool_row = ("layers", None, None, "kv_heads", "head_dim")
    return {
        "rules": rules,
        "params": tree_shardings(mesh, decode_param_axes(config), rules),
        "cache": {"k": ns(*kv_row), "v": ns(*kv_row),
                  "length": NamedSharding(mesh, PartitionSpec())},
        "pool": {"k": ns(*pool_row), "v": ns(*pool_row),
                 "length": NamedSharding(mesh, PartitionSpec())},
        "prefix_pool": {"k": ns(*pool_row), "v": ns(*pool_row)},
        "replicated": NamedSharding(mesh, PartitionSpec()),
    }


def shard_decode_state(params: Dict[str, Any], config: LlamaConfig,
                       mesh) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Device-put ``params`` onto ``mesh`` with the decode shardings.
    Returns ``(sharded_params, shardings_bundle)`` — the engine commits
    the weights once at construction; the jitted programs inherit the
    committed input shardings and pin their outputs with the bundle."""
    shardings = decode_shardings(config, mesh)
    return jax.device_put(params, shardings["params"]), shardings


def _sample(logits: jax.Array, temperature: float, key) -> jax.Array:
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, logits / temperature, axis=-1).astype(jnp.int32)


def sample_batch(logits: jax.Array, temperatures: jax.Array,
                 key) -> jax.Array:
    """Per-row sampling fused into decode programs: greedy argmax where
    ``temperatures[b] <= 0`` else categorical at that row's temperature.
    The greedy lane is bit-identical to host ``np.argmax`` (both take
    the first maximum); the sampled lane draws from the device RNG
    stream, which intentionally differs from the host sampler's numpy
    stream — callers opt in via the ``decode_device_sampler`` knob."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temps = jnp.maximum(temperatures, 1e-6)[:, None]
    sampled = jax.random.categorical(
        key, logits / temps, axis=-1).astype(jnp.int32)
    return jnp.where(temperatures <= 0.0, greedy, sampled)


@partial(jax.jit, static_argnames=("config", "max_new_tokens",
                                   "temperature", "eos_id"))
def _generate_jit(params, tokens, lengths, key, config: LlamaConfig,
                  max_new_tokens: int, temperature: float,
                  eos_id: int):
    B, S = tokens.shape
    capacity = cache_bucket(S + max_new_tokens)
    cache = init_cache(config, B, capacity)
    logits, cache = prefill(params, tokens, cache, config, lengths)
    key, sub = jax.random.split(key)
    first = _sample(logits, temperature, sub)
    done0 = (first == eos_id) if eos_id >= 0 else jnp.zeros(B, bool)

    def step(carry, _):
        cache, tok, key, done = carry
        logits, cache = decode_step(params, cache, tok, config)
        key, sub = jax.random.split(key)
        nxt = _sample(logits, temperature, sub)
        nxt = jnp.where(done, eos_id if eos_id >= 0 else 0, nxt)
        done = done | ((nxt == eos_id) if eos_id >= 0 else False)
        return (cache, nxt, key, done), nxt

    (_, _, _, _), rest = jax.lax.scan(
        step, (cache, first, key, done0), None,
        length=max_new_tokens - 1)
    return jnp.concatenate([first[None], rest], axis=0).T  # (B, max_new)


def generate(params: Dict[str, Any], tokens, config: LlamaConfig,
             max_new_tokens: int = 32, temperature: float = 0.0,
             key=None, eos_id: Optional[int] = None,
             lengths=None) -> jax.Array:
    """Generate ``max_new_tokens`` per prompt row as ONE jitted program
    (prefill + scanned decode): the benchmark/offline path. Serving uses
    ``prefill``/``decode_step`` directly through ``serve/decode.py`` so
    requests can join/leave the batch between steps."""
    tokens = jnp.asarray(tokens, jnp.int32)
    if tokens.ndim == 1:
        tokens = tokens[None]
    if key is None:
        key = jax.random.key(0)
    if lengths is not None:
        lengths = jnp.asarray(lengths, jnp.int32)
    return _generate_jit(params, tokens, lengths, key, config,
                         int(max_new_tokens), float(temperature),
                         -1 if eos_id is None else int(eos_id))
