"""Vision Transformer: the image-model family.

Second model family beside Llama (SURVEY §7 phase 4 names ViT as the
north-star Train/Tune workload — the reference's analogous benchmarks train
ResNet/vision models through Ray Train, ``doc/source/train/benchmarks.rst``).
Same TPU-first idiom as ``models/llama.py``:

* patch embedding as ONE einsum over reshaped patches (a conv with
  stride = kernel = patch collapses to a matmul — MXU-shaped, no XLA conv
  needed for the stem);
* encoder blocks stacked on a leading ``layers`` axis, executed by
  ``lax.scan`` with optional ``jax.checkpoint`` (compile once per depth);
* logical-axis sharding annotations (``constrain``) so the same code runs
  DP/FSDP/TP on any mesh via the rule table in ``parallel/sharding.py``;
* bf16 compute / fp32 master params, fp32 softmax-CE loss.

Mean-pool classification head (no CLS token): equivalent accuracy at this
scale and one less ragged token to shard.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import constrain


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    channels: int = 3
    num_classes: int = 10
    dim: int = 192
    n_layers: int = 6
    n_heads: int = 3
    mlp_dim: int = 768
    dropout: float = 0.0          # kept for API parity; eval path ignores
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # None = full remat; "dots" keeps matmul outputs (recompute only the
    # cheap elementwise work — more memory, fewer recomputed FLOPs).
    remat_policy: Any = None
    # Pad the token axis to this length inside the model (masked, exact):
    # ViT-B/16's 196 tokens ride 8x128 MXU tiles badly (1.53 lane tiles);
    # 256 tiles perfectly. Padded tokens get zero attention weight and
    # are excluded from the pool, so the math is unchanged — only the
    # tiling improves. None = no padding.
    pad_tokens_to: Optional[int] = None

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels


PRESETS: Dict[str, ViTConfig] = {
    "debug": ViTConfig(image_size=16, patch_size=4, dim=64, n_layers=2,
                       n_heads=2, mlp_dim=128, num_classes=10),
    "vit_s16_cifar": ViTConfig(image_size=32, patch_size=4, dim=384,
                               n_layers=12, n_heads=6, mlp_dim=1536),
    "vit_b16": ViTConfig(image_size=224, patch_size=16, dim=768,
                         n_layers=12, n_heads=12, mlp_dim=3072,
                         num_classes=1000),
}


def param_axes(config: Optional[ViTConfig] = None) -> Dict[str, Any]:
    """Logical axis names mirroring the params pytree (same rule table as
    Llama: embed->fsdp, heads/mlp->tensor, batch->(data, fsdp))."""
    return {
        "patch_embed": ("patch", "embed"),
        "pos_embed": ("length", "embed"),
        "layers": {
            "ln1_scale": ("layers", "embed"),
            "ln1_bias": ("layers", "embed"),
            "wqkv": ("layers", "embed", "heads", "head_dim"),
            "wo": ("layers", "heads", "head_dim", "embed"),
            "ln2_scale": ("layers", "embed"),
            "ln2_bias": ("layers", "embed"),
            "w_up": ("layers", "embed", "mlp"),
            "b_up": ("layers", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
            "b_down": ("layers", "embed"),
        },
        "final_ln_scale": ("embed",),
        "final_ln_bias": ("embed",),
        "head": ("embed", "vocab"),   # classes shard like vocab
        "head_bias": ("vocab",),
    }


def init_params(config: ViTConfig, key: jax.Array,
                dtype=jnp.float32) -> Dict[str, Any]:
    c = config
    k_patch, k_pos, k_layers, k_head = jax.random.split(key, 4)

    def trunc(key, shape, scale):
        return (jax.random.truncated_normal(key, -2, 2, shape, dtype)
                * scale)

    L = c.n_layers
    lk = jax.random.split(k_layers, 4)
    layers = {
        "ln1_scale": jnp.ones((L, c.dim), dtype),
        "ln1_bias": jnp.zeros((L, c.dim), dtype),
        "wqkv": trunc(lk[0], (L, c.dim, 3 * c.n_heads, c.head_dim),
                      c.dim ** -0.5),
        "wo": trunc(lk[1], (L, c.n_heads, c.head_dim, c.dim),
                    (c.n_heads * c.head_dim) ** -0.5),
        "ln2_scale": jnp.ones((L, c.dim), dtype),
        "ln2_bias": jnp.zeros((L, c.dim), dtype),
        "w_up": trunc(lk[2], (L, c.dim, c.mlp_dim), c.dim ** -0.5),
        "b_up": jnp.zeros((L, c.mlp_dim), dtype),
        "w_down": trunc(lk[3], (L, c.mlp_dim, c.dim), c.mlp_dim ** -0.5),
        "b_down": jnp.zeros((L, c.dim), dtype),
    }
    return {
        "patch_embed": trunc(k_patch, (c.patch_dim, c.dim),
                             c.patch_dim ** -0.5),
        "pos_embed": trunc(k_pos, (c.num_patches, c.dim), 0.02),
        "layers": layers,
        "final_ln_scale": jnp.ones((c.dim,), dtype),
        "final_ln_bias": jnp.zeros((c.dim,), dtype),
        "head": jnp.zeros((c.dim, c.num_classes), dtype),
        "head_bias": jnp.zeros((c.num_classes,), dtype),
    }


def _layer_norm(x, scale, bias, eps=1e-6):
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.var(x, axis=-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * scale + bias


def patchify(images: jax.Array, config: ViTConfig) -> jax.Array:
    """(B, H, W, C) -> (B, N_patches, patch_dim) by pure reshapes (the
    stride-p conv stem as a matmul's input layout)."""
    c = config
    b, h, w, ch = images.shape
    p = c.patch_size
    x = images.reshape(b, h // p, p, w // p, p, ch)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (h // p) * (w // p), p * p * ch)


def _block(x, layer, c: ViTConfig, n_valid: Optional[int] = None):
    h = _layer_norm(x, layer["ln1_scale"], layer["ln1_bias"])
    h = constrain(h, ("batch", "length", "act_embed"))
    qkv = jnp.einsum("bne,ehd->bnhd", h, layer["wqkv"].astype(c.dtype))
    q, k, v = jnp.split(qkv, 3, axis=2)
    q = constrain(q, ("batch", "length", "heads", "head_dim"))
    from ray_tpu.ops.attention import attention

    # scale applied in the kernel; tile-padding keys masked out
    out = attention(q, k, v, causal=False, kv_valid=n_valid)
    # Pre-contraction anchors, same idiom as llama._decoder_layer: the
    # attention output entering wo and the ffn hidden entering w_down
    # use the ANCHOR axes (attn_heads/mlp_hidden = "tensor" under train
    # rules, exactly what propagation picks, and None under DECODE
    # rules so no reduction is ever split across the mesh).
    out = constrain(out, ("batch", "length", "attn_heads", "head_dim"))
    out = jnp.einsum("bnhd,hde->bne", out, layer["wo"].astype(c.dtype))
    x = x + constrain(out, ("batch", "length", "act_embed"))

    h2 = _layer_norm(x, layer["ln2_scale"], layer["ln2_bias"])
    up = jnp.einsum("bne,em->bnm", h2, layer["w_up"].astype(c.dtype))
    up = jax.nn.gelu(up + layer["b_up"].astype(c.dtype))
    up = constrain(up, ("batch", "length", "mlp_hidden"))
    down = jnp.einsum("bnm,me->bne", up, layer["w_down"].astype(c.dtype))
    down = down + layer["b_down"].astype(c.dtype)
    return x + constrain(down, ("batch", "length", "act_embed"))


def forward(params: Dict[str, Any], images: jax.Array,
            config: ViTConfig) -> jax.Array:
    """Images (B, H, W, C) float -> class logits (B, num_classes) fp32."""
    c = config
    patches = patchify(images.astype(c.dtype), c)
    x = jnp.einsum("bnp,pe->bne", patches,
                   params["patch_embed"].astype(c.dtype))
    x = x + params["pos_embed"].astype(c.dtype)
    n_tokens = x.shape[1]
    n_valid = None
    if c.pad_tokens_to and c.pad_tokens_to > n_tokens:
        # Tile-friendly token padding (masked, exact — see the config
        # field). Padded rows carry zeros; attention masks them as keys
        # and the pool slices them off, so only the MXU tiling changes.
        x = jnp.pad(x, ((0, 0), (0, c.pad_tokens_to - n_tokens), (0, 0)))
        n_valid = n_tokens
    x = constrain(x, ("batch", "length", "act_embed"))

    def body(carry, layer):
        layer = {k: v.astype(c.dtype) if v.dtype == jnp.float32 else v
                 for k, v in layer.items()}
        return _block(carry, layer, c, n_valid), None

    if c.remat and c.remat_policy == "dots":
        scan_body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    elif c.remat:
        scan_body = jax.checkpoint(body)
    else:
        scan_body = body
    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    x = x[:, :n_tokens]
    x = _layer_norm(x, params["final_ln_scale"].astype(c.dtype),
                    params["final_ln_bias"].astype(c.dtype))
    pooled = jnp.mean(x, axis=1)  # mean-pool head
    logits = jnp.einsum("be,ec->bc", pooled,
                        params["head"].astype(c.dtype),
                        preferred_element_type=jnp.float32)
    return logits + params["head_bias"].astype(jnp.float32)


def loss_fn(params: Dict[str, Any], batch: Dict[str, jax.Array],
            config: ViTConfig) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Softmax cross entropy; returns (loss, {"accuracy": ...})."""
    logits = forward(params, batch["images"], config)
    labels = batch["labels"]
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    loss = jnp.mean(logz - gold)
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"accuracy": acc}


def flops_per_image(config: ViTConfig) -> float:
    """Training FLOPs per image, same convention as
    ``llama.flops_per_token`` (fwd+bwd ~= 6*N per token plus the
    attention quadratic term). This model is mean-pool (NO CLS token), so
    tokens = num_patches; the classifier head runs ONCE per image on the
    pooled vector, and positional embeddings do no matmul work — neither
    may be counted per-token."""
    c = config
    tokens = c.num_patches
    head_params = c.dim * c.num_classes
    pos_params = c.num_patches * c.dim
    per_token_params = num_params(c) - head_params - pos_params
    param_flops = 6.0 * per_token_params * tokens + 6.0 * head_params
    attn_flops = 12.0 * c.n_layers * c.dim * tokens * tokens
    return param_flops + attn_flops


def num_params(config: ViTConfig) -> int:
    leaves = jax.tree.leaves(
        jax.eval_shape(lambda: init_params(config, jax.random.key(0))))
    return sum(int(jnp.prod(jnp.array(l.shape))) for l in leaves)
