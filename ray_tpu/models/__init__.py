"""Model families (TPU-first: scanned layers, GSPMD logical axes).

``llama`` — decoder-only LM (flash/ring/Ulysses attention, MoE variant).
``vit`` — Vision Transformer image classifier.
"""

from ray_tpu.models import llama, vit  # noqa: F401
