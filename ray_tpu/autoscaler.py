"""Autoscaler: demand-driven node provisioning.

Analogue of the reference's ``StandardAutoscaler``
(``autoscaler/_private/autoscaler.py:172,374``): a control loop reads the
cluster's load (alive nodes' availability + queue depth, plus the demand
the scheduler could not place), bin-packs the unmet demand onto candidate
node types, launches nodes through a pluggable ``NodeProvider``, and
terminates nodes idle past a timeout.

Providers:

* ``FakeMultiNodeProvider`` — launches real in-process ``Node`` supervisors
  (the reference's ``fake_multi_node/node_provider.py`` trick: autoscaler
  logic runs against real raylets on one machine).
* ``TPUVMNodeProvider`` — the TPU-era cloud provider shape (reference: the
  GCP provider speaking the TPU VM API, ``gcp/node_provider.py:75-94`` +
  ``tpu_command_runner.py``): creates whole pod SLICES as atomic gangs.
  This image has zero egress, so the GCE/TPU API calls are delegated to an
  injected transport; the provisioning logic (slice sizing, gang
  atomicity, idle teardown) is real and tested via the fake transport.

The demand signal rides the controller: ``pick_node`` failures record the
unplaceable resource shapes, exposed via the ``autoscaler_state`` RPC
(reference: ``GcsAutoscalerStateManager`` over ``autoscaler.proto``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.core import resources as resmath


class NodeProvider:
    """Pluggable provisioning backend (reference: ``node_provider.py``)."""

    def create_node(self, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Launches real in-process node supervisors against the given
    controller — the reference's fake-multi-node testing trick."""

    def __init__(self, controller_addr):
        self._controller_addr = controller_addr
        self._nodes: Dict[str, Any] = {}
        self._counter = 0

    def create_node(self, resources, labels) -> str:
        from ray_tpu.core.node import Node

        self._counter += 1
        pid = f"fake-{self._counter}"
        node = Node(self._controller_addr, dict(resources),
                    {**labels, "provider_node_id": pid})
        self._nodes[pid] = node
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        node = self._nodes.pop(provider_node_id, None)
        if node is not None:
            node.stop()

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def node_id_of(self, provider_node_id: str) -> Optional[str]:
        node = self._nodes.get(provider_node_id)
        return node.node_id.hex() if node else None


class TPUVMNodeProvider(NodeProvider):
    """TPU-VM slices as atomic gangs over the real REST client
    (:class:`ray_tpu.tpu_vm_api.TpuVmClient`; reference: the GCP provider
    speaking the TPU VM API, ``gcp/node_provider.py:75-94``). One "node" =
    one pod slice; a slice's resources advertise every chip (``TPU:
    chips``) plus the slice-topology label the gang scheduler keys on.

    ``bootstrap(node_dict, labels)`` — when given — runs after a created
    slice turns READY (the launcher uses it to SSH ``ray_tpu start`` onto
    every slice host via :class:`TPUPodCommandRunner`); tests and dry-run
    skip it. ``transport``/legacy 3-arg transports are adapted for tests
    that fake the HTTP layer."""

    def __init__(self, transport=None, project: str = "", zone: str = "",
                 accelerator_type: str = "v5litepod-16",
                 runtime_version: str = "v2-alpha-tpuv5-lite",
                 client=None,
                 bootstrap: "Optional[Callable[[dict, Dict], None]]" = None,
                 name_prefix: str = "ray-tpu-slice",
                 filter_labels: "Optional[Dict[str, str]]" = None):
        if client is None:
            from ray_tpu.tpu_vm_api import TpuVmClient

            if transport is not None:
                # Legacy test transports take (verb, path, body); the
                # client calls (verb, url, body, headers). No token needed
                # against a fake.
                def adapted(verb, url, body, headers, _t=transport):
                    path = url.split("/v2/", 1)[-1]
                    return _t(verb, path, body)

                client = TpuVmClient(project, zone, token_fn=lambda: "",
                                     transport=adapted)
            else:
                # Real HTTP: default auth (GCE metadata-server token).
                client = TpuVmClient(project, zone)
        self._client = client
        self._accelerator_type = accelerator_type
        self._runtime_version = runtime_version
        self._bootstrap = bootstrap
        self._name_prefix = name_prefix
        # Only nodes carrying ALL these labels belong to this provider:
        # the project/zone is shared real estate — without the filter,
        # idle teardown and shutdown would delete the head and other
        # clusters' slices, and the provisioning count would see phantoms.
        self._filter_labels = dict(filter_labels or {})
        self._counter = 0

    def create_node(self, resources, labels) -> str:
        import json as _json

        self._counter += 1
        name = f"{self._name_prefix}-{self._counter}"
        op = self._client.create_node(
            name,
            self._accelerator_type,
            self._runtime_version,
            # provider_node_id is the SHORT node name: GCP label values are
            # [a-z0-9_-] and <= 63 chars, so the full resource path (with
            # slashes) would be rejected by the live API. The slice's
            # raylets start with this label so the autoscaler can map
            # cluster nodes back to provider instances.
            labels={**self._filter_labels, **labels,
                    "provider_node_id": name},
            metadata={"ray_resources": _json.dumps(dict(resources))},
        )
        if self._bootstrap is not None:
            self._client.wait_operation(op)
            node = self._client.get_node(self._node_path(name))
            self._bootstrap(node, {**labels, "provider_node_id": name})
        return name

    def _node_path(self, provider_node_id: str) -> str:
        if "/" in provider_node_id:  # already a full resource path
            return provider_node_id
        return f"{self._client.parent}/nodes/{provider_node_id}"

    def terminate_node(self, provider_node_id: str) -> None:
        self._client.delete_node(self._node_path(provider_node_id))

    def non_terminated_nodes(self) -> List[str]:
        out = []
        for n in self._client.list_nodes():
            if n.get("state") in ("DELETING", "TERMINATED"):
                continue
            node_labels = n.get("labels", {})
            if any(node_labels.get(k) != v
                   for k, v in self._filter_labels.items()):
                continue
            out.append(n["name"].rsplit("/", 1)[-1])
        return out


class _RemoteController:
    """Adapter: drive the autoscaler against a cluster's controller RPC
    endpoint instead of an in-process Controller object."""

    def __init__(self, client):
        self._client = client

    def autoscaler_state(self):
        return self._client.call("autoscaler_state")


class StandardAutoscaler:
    """The reference's update() loop shape: observe -> plan -> act."""

    def __init__(self, controller, provider: NodeProvider,
                 node_resources: Dict[str, float],
                 min_nodes: int = 0, max_nodes: int = 8,
                 idle_timeout_s: float = 60.0,
                 update_interval_s: float = 1.0,
                 node_labels: "Optional[Dict[str, str]]" = None,
                 instance_manager=None):
        if hasattr(controller, "call") and not hasattr(
                controller, "autoscaler_state"):
            controller = _RemoteController(controller)
        self._controller = controller
        self._provider = provider
        # Optional lifecycle layer (reference: autoscaler/v2
        # instance_manager + updater.py's retry/backoff setup): when
        # present, the planner requests/terminates THROUGH it and it owns
        # allocation retries, setup backoff, and stuck-instance
        # replacement.
        self._im = instance_manager
        self._node_resources = dict(node_resources)
        self._node_labels = dict(node_labels or {})
        self._min_nodes = min_nodes
        self._max_nodes = max_nodes
        self._idle_timeout_s = idle_timeout_s
        self._update_interval_s = update_interval_s
        self._idle_since: Dict[str, float] = {}  # node hex -> ts
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_launches = 0
        self.num_terminations = 0

    # ------------------------------------------------------------ control

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        import sys

        warned = False
        while not self._stop.wait(self._update_interval_s):
            try:
                self.update()
                warned = False
            except Exception as e:  # noqa: BLE001
                if not warned:  # a dead autoscaler must not be silent
                    print(f"autoscaler: update failing: {e!r}",
                          file=sys.stderr)
                    warned = True

    # ------------------------------------------------------------- update

    def update(self) -> None:
        """One reconcile pass (reference: StandardAutoscaler.update,
        autoscaler.py:374)."""
        state = self._controller.autoscaler_state()
        nodes = [n for n in state["nodes"] if n["alive"]]
        # Hosts the autopilot demoted from placement (heartbeat-RTT
        # outliers etc.): still alive, but their free capacity must not
        # absorb pending demand below — otherwise the demand looks met
        # and no healthy replacement ever launches. They still count
        # against max_nodes and still scale down when idle.
        tainted = set(state.get("tainted", ()))
        # Demand entries: {"resources": ..., "labels": ...} (labels from
        # node_label-blocked tasks). A label-constrained demand only counts
        # against this autoscaler's node type if the template labels
        # satisfy it — otherwise launching would never help — and, below,
        # only label-satisfying nodes' capacity can absorb it (a label-less
        # head node's free CPUs must not mark {pool: tpu} demand as met).
        def label_ok(node_labels, want):
            return not want or all(node_labels.get(k) == v
                                   for k, v in want.items())

        demand = []  # (resources, labels-or-None)
        for entry in state["pending_demand"]:
            if isinstance(entry, dict) and "resources" in entry:
                labels = entry.get("labels")
                if not label_ok(self._node_labels, labels):
                    continue
                demand.append((entry["resources"], labels))
            else:  # legacy plain resource dict
                demand.append((entry, None))
        provider_ids = set(self._provider.non_terminated_nodes())
        registered = {n["labels"].get("provider_node_id")
                      for n in nodes}
        if self._im is not None:
            self._im.reconcile(registered)

        # Plan scale-up: bin-pack unmet demand onto hypothetical new nodes.
        # Launched-but-not-yet-registered nodes count as capacity so slow
        # provisioning (minutes for a TPU slice) doesn't relaunch the same
        # demand every tick (with an instance manager, REQUESTED-but-not-
        # yet-allocated instances count too).
        # Provider-visible-but-unregistered nodes count even with an
        # instance manager: its state is in-memory, so after a restart it
        # would not know about a TPU slice still provisioning — and a
        # duplicate launch for the same demand is the expensive mistake.
        provisioning = len(provider_ids - registered)
        if self._im is not None:
            provisioning += self._im.requested_count()
        unmet: List[tuple] = []
        capacity = ([(n.get("labels", {}), dict(n["available"]))
                     for n in nodes if n["node_id"] not in tainted]
                    + [(self._node_labels, dict(self._node_resources))
                       for _ in range(provisioning)])
        for shape, want in demand:
            if not any(label_ok(lbls, want) and resmath.fits(c, shape)
                       and resmath.take(c, shape)
                       for lbls, c in capacity):
                unmet.append((shape, want))
        to_launch = 0
        new_node = dict(self._node_resources)
        pool: Dict[str, float] = {}
        for shape, _want in unmet:  # template labels already vetted above
            if not resmath.fits(new_node, shape):
                continue  # this node type can never satisfy it
            if not (pool and resmath.take(pool, shape)):
                to_launch += 1
                pool = dict(new_node)
                resmath.take(pool, shape)
        def current_count() -> int:
            live = len(self._provider.non_terminated_nodes())
            if self._im is not None:
                # Provider view + not-yet-allocated requests.
                return live + self._im.requested_count()
            return live

        def launch_one() -> None:
            if self._im is not None:
                self._im.request_node(self._node_resources,
                                      dict(self._node_labels))
            else:
                self._provider.create_node(self._node_resources,
                                           dict(self._node_labels))
            self.num_launches += 1

        launchable = max(0, min(to_launch,
                                self._max_nodes - current_count()))
        for _ in range(launchable):
            launch_one()

        # Ensure the floor.
        for _ in range(max(0, self._min_nodes - current_count())):
            launch_one()

        # Plan scale-down: terminate nodes idle past the timeout. Any
        # provider works: nodes carry their provider instance id as the
        # "provider_node_id" label.
        now = time.monotonic()
        remaining = len(nodes)
        for n in list(nodes):
            busy = (n["queue_len"] > 0
                    or any(n["available"].get(k, 0) < v
                           for k, v in n["resources"].items()))
            if busy:
                self._idle_since.pop(n["node_id"], None)
                continue
            pid = n["labels"].get("provider_node_id")
            first_idle = self._idle_since.setdefault(n["node_id"], now)
            if (now - first_idle > self._idle_timeout_s
                    and remaining > self._min_nodes
                    and pid in provider_ids):
                # Count the DECISION before executing it: terminate_node
                # can take seconds (socket teardown, thread joins) while
                # the provider's list already shows the node gone —
                # observers polling (provider empty, counter) must never
                # see the torn intermediate state.
                self._idle_since.pop(n["node_id"], None)
                self.num_terminations += 1
                remaining -= 1
                if self._im is not None:
                    self._im.terminate(pid)
                else:
                    self._provider.terminate_node(pid)
