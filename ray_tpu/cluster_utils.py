"""Multi-node-in-one-machine cluster fixture.

Analogue of the reference's ``python/ray/cluster_utils.py:135`` ``Cluster`` —
the backbone of all distributed testing (SURVEY §4: "multiple raylets on one
machine emulate multi-node"). Each ``add_node`` starts a real node supervisor
(its own RPC server, worker pool and resource accounting) in this process;
workers are real subprocesses, so scheduling, spillback, object pulls and
node-death paths exercise the same code as a physical cluster.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ray_tpu.core.controller import Controller
from ray_tpu.core.node import Node


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None,
                 controller_kwargs: Optional[Dict] = None):
        self._controller_kwargs = dict(controller_kwargs or {})
        self.controller = Controller(**self._controller_kwargs)
        self.nodes = []
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    def crash_controller(self) -> None:
        """Simulate a head crash: the control-plane process dies without a
        graceful final snapshot (its periodic persist loop may have saved).
        Raylets and workers stay up."""
        self.controller._stopped.set()
        self.controller._server.stop()
        self.controller._clients.close_all()
        # Drain the old persist loop before a replacement can share the
        # snapshot path: _save_lock is per-instance, so without this join
        # two controllers could interleave writes on the same .tmp file.
        persist = getattr(self.controller, "_persist_thread", None)
        if persist is not None:
            persist.join(timeout=10.0)

    def restart_controller(self) -> Controller:
        """Start a replacement controller on the SAME address (head
        fault-tolerance: raylets re-register via heartbeats; persisted
        state — KV, jobs, named actors, actor records — restores from the
        snapshot when ``persist_path`` was configured)."""
        kwargs = dict(self._controller_kwargs)
        host, port = self.controller.address
        kwargs.update(host=host, port=port)
        self.controller = Controller(**kwargs)
        return self.controller

    @property
    def address(self):
        return self.controller.address

    def add_node(self, num_cpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None) -> Node:
        node_resources = dict(resources or {})
        if num_cpus is not None:
            node_resources["CPU"] = float(num_cpus)
        node = Node(self.controller.address, node_resources, labels)
        self.nodes.append(node)
        return node

    def remove_node(self, node: Node) -> None:
        node.stop()
        if node in self.nodes:
            self.nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        want = len(self.nodes)
        while time.monotonic() < deadline:
            alive = [n for n in self.controller.list_nodes() if n["alive"]]
            if len(alive) >= want:
                return
            time.sleep(0.05)
        raise TimeoutError(f"only {len(alive)}/{want} nodes alive")

    def shutdown(self) -> None:
        for node in self.nodes:
            try:
                node.stop()
            except Exception:  # graftlint: disable=swallowed-exception (best-effort fixture teardown)
                pass
        self.nodes.clear()
        self.controller.stop()


class WorkerKiller:
    """Chaos utility: randomly SIGKILLs worker processes while a workload
    runs (reference: ``_ray_start_chaos_cluster`` + ``WorkerKillerActor``,
    ``python/ray/_private/test_utils.py:1562``). Tasks must still complete
    through owner-side retries."""

    def __init__(self, nodes, period_s: float = 0.5, seed: int = 0):
        import random
        import threading

        self._nodes = list(nodes)
        self._period = period_s
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="worker-killer")
        self.kills = 0

    def start(self) -> "WorkerKiller":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._period):
            node = self._rng.choice(self._nodes)
            if node.kill_random_pooled_worker(self._rng):
                self.kills += 1

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
