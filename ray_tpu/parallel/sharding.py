"""Logical-axis sharding rules: how tensors map onto the mesh.

The TPU-native replacement for everything the reference delegates to torch
DDP/FSDP/DeepSpeed (SURVEY §2.4): parameters and activations carry *logical*
axis names (``("vocab", "embed")``), and a rule table maps logical axes to
mesh axes. Changing parallelism strategy = changing the rule table; the model
code never changes (t5x/MaxText-style GSPMD idiom).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicate)
Rules = Dict[str, Union[str, Tuple[str, ...], None]]

# Megatron-style transformer rules. The load-bearing choices:
# * batch over (data, fsdp): gradients psum over both -> plain DP semantics.
# * embed over fsdp: ZeRO-3 — params/optimizer state sharded, all-gathered
#   per layer by XLA (with remat this is the standard FSDP schedule).
# * heads/mlp over tensor: Megatron column->row pairs; XLA inserts the
#   all-reduce at the row-parallel output exactly like hand-written TP.
# * length over seq: context parallelism; attention uses ring_attention
#   (ray_tpu.parallel.ring_attention) so no gather of the full sequence.
# * experts over expert axis: MoE expert sharding, all-to-all routed.
DEFAULT_RULES: Rules = {
    "batch": ("data", "fsdp"),
    "length": "seq",
    "vocab": "tensor",
    "embed": "fsdp",
    # Activations keep the embed dim unsharded (batch already covers fsdp;
    # a duplicate mesh axis in one spec is illegal and embed-sharded
    # activations would force per-op all-to-alls).
    "act_embed": None,
    "heads": "tensor",
    "kv_heads": "tensor",
    "head_dim": None,
    "mlp": "tensor",
    "experts": "expert",
    "expert": "expert",      # stacked per-expert weights (MoE)
    "expert_dim": None,      # router output dim (E as a feature axis)
    "layers": None,  # scanned-layer leading axis
    "norm": None,
    "patch": None,   # ViT patch-pixel input axis
}


def spec_for(logical_axes: Sequence[Optional[str]],
             rules: Optional[Rules] = None) -> P:
    """Translate a tuple of logical axis names into a PartitionSpec.
    ``None`` (the whole tuple) means fully replicated."""
    if logical_axes is None:
        return P()
    rules = rules or DEFAULT_RULES
    parts = []
    for ax in logical_axes:
        if ax is None:
            parts.append(None)
        else:
            if ax not in rules:
                raise KeyError(f"no sharding rule for logical axis {ax!r}")
            parts.append(rules[ax])
    # Trim trailing Nones for cleaner specs.
    while parts and parts[-1] is None:
        parts.pop()
    return P(*parts)


def tree_shardings(mesh: Mesh, axes_tree: Any,
                   rules: Optional[Rules] = None):
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, spec_for(axes, rules)),
        axes_tree,
        is_leaf=lambda x: isinstance(x, tuple) or x is None,
    )


def shard_tree(tree: Any, shardings: Any):
    """Device-put a pytree with the given shardings."""
    return jax.tree.map(jax.device_put, tree, shardings)


_ctx = threading.local()


@contextmanager
def axis_rules(mesh: Mesh, rules: Optional[Rules] = None):
    """Set the (mesh, rules) context under which ``constrain`` resolves
    logical axes. Train-step builders trace model code inside this context;
    model code stays mesh-agnostic (t5x ``axis_rules`` idiom)."""
    prev = getattr(_ctx, "value", None)
    _ctx.value = (mesh, rules or DEFAULT_RULES)
    try:
        yield
    finally:
        _ctx.value = prev


def constrain(x: jax.Array, logical_axes: Sequence[Optional[str]]):
    """Apply a GSPMD sharding constraint by logical axis names; no-op when
    no axis_rules context is active (single-device paths, tests)."""
    ctx = getattr(_ctx, "value", None)
    if ctx is None:
        return x
    mesh, rules = ctx
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec_for(logical_axes, rules)))


def current_mesh() -> Optional[Mesh]:
    ctx = getattr(_ctx, "value", None)
    return None if ctx is None else ctx[0]


def batch_sharding(mesh: Mesh, rules: Optional[Rules] = None) -> NamedSharding:
    """Sharding for (batch, length, ...) input batches."""
    return NamedSharding(mesh, spec_for(("batch", "length"), rules))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
