"""Logical-axis sharding rules: how tensors map onto the mesh.

The TPU-native replacement for everything the reference delegates to torch
DDP/FSDP/DeepSpeed (SURVEY §2.4): parameters and activations carry *logical*
axis names (``("vocab", "embed")``), and a rule table maps logical axes to
mesh axes. Changing parallelism strategy = changing the rule table; the model
code never changes (t5x/MaxText-style GSPMD idiom).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicate)
Rules = Dict[str, Union[str, Tuple[str, ...], None]]

# Megatron-style transformer rules. The load-bearing choices:
# * batch over (data, fsdp): gradients psum over both -> plain DP semantics.
# * embed over fsdp: ZeRO-3 — params/optimizer state sharded, all-gathered
#   per layer by XLA (with remat this is the standard FSDP schedule).
# * heads/mlp over tensor: Megatron column->row pairs; XLA inserts the
#   all-reduce at the row-parallel output exactly like hand-written TP.
# * length over seq: context parallelism; attention uses ring_attention
#   (ray_tpu.parallel.ring_attention) so no gather of the full sequence.
# * experts over expert axis: MoE expert sharding, all-to-all routed.
DEFAULT_RULES: Rules = {
    "batch": ("data", "fsdp"),
    "length": "seq",
    "vocab": "tensor",
    "embed": "fsdp",
    # Activations keep the embed dim unsharded (batch already covers fsdp;
    # a duplicate mesh axis in one spec is illegal and embed-sharded
    # activations would force per-op all-to-alls).
    "act_embed": None,
    "heads": "tensor",
    "kv_heads": "tensor",
    "head_dim": None,
    "mlp": "tensor",
    # Pre-contraction anchors: the attention output entering the wo
    # projection and the ffn hidden entering w_down. Under the train rules
    # these equal what propagation already picks (tensor-sharded — the
    # Megatron row-parallel input), so constraining them is free; the
    # DECODE rules map them to None instead, forcing an all-gather BEFORE
    # the contraction so no reduction is ever split across the mesh (the
    # bit-exactness contract of sharded serving).
    "attn_heads": "tensor",
    "mlp_hidden": "tensor",
    "experts": "expert",
    "expert": "expert",      # stacked per-expert weights (MoE)
    "expert_dim": None,      # router output dim (E as a feature axis)
    "layers": None,  # scanned-layer leading axis
    "norm": None,
    "patch": None,   # ViT patch-pixel input axis
}


def spec_for(logical_axes: Sequence[Optional[str]],
             rules: Optional[Rules] = None) -> P:
    """Translate a tuple of logical axis names into a PartitionSpec.
    ``None`` (the whole tuple) means fully replicated."""
    if logical_axes is None:
        return P()
    rules = rules or DEFAULT_RULES
    parts = []
    for ax in logical_axes:
        if ax is None:
            parts.append(None)
        else:
            if ax not in rules:
                raise KeyError(f"no sharding rule for logical axis {ax!r}")
            parts.append(rules[ax])
    # Trim trailing Nones for cleaner specs.
    while parts and parts[-1] is None:
        parts.pop()
    return P(*parts)


def tree_shardings(mesh: Mesh, axes_tree: Any,
                   rules: Optional[Rules] = None):
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, spec_for(axes, rules)),
        axes_tree,
        is_leaf=lambda x: isinstance(x, tuple) or x is None,
    )


def shard_tree(tree: Any, shardings: Any):
    """Device-put a pytree with the given shardings."""
    return jax.tree.map(jax.device_put, tree, shardings)


# Serving (GSPMD model-parallel decode) rules over the 2-axis
# ``decode_mesh`` (("batch", "model"), parallel.mesh.DECODE_AXES). The
# load-bearing difference from DEFAULT_RULES: **no contraction dimension
# is ever partitioned.** Output dims shard (heads/kv_heads/mlp/vocab over
# "model", slots over "batch"); the pre-contraction anchors
# (attn_heads/mlp_hidden) replicate, so XLA inserts all-gathers instead
# of psums and every output element is produced by the exact reduction
# order of the single-chip program — sharded decode logits are BIT-EXACT
# vs the single-chip engine (the serve plane's correctness contract; the
# cost is that wo / w_down stay replicated, see
# ``llama.decode_param_axes``).
DECODE_RULES: Rules = {
    "batch": "batch",
    "length": None,
    "vocab": "model",
    "embed": None,         # contracted by every projection: never shard
    "act_embed": None,
    "heads": "model",
    "kv_heads": "model",
    "head_dim": None,
    "mlp": "model",
    "attn_heads": None,    # all-gather before the wo contraction
    "mlp_hidden": None,    # all-gather before the w_down contraction
    "layers": None,
    "norm": None,
    "patch": None,
}


# ZeRO-1 optimizer-state overlay ("Automatic Cross-Replica Sharding of
# Weight Update in Data-Parallel Training", PAPERS.md): optimizer state
# (mu/nu, fp32 master copies) is sharded across the DATA axis as a
# sharding annotation — each replica keeps 1/N of the state, updates its
# shard, and the updated params are all-gathered once per step
# (train_step.build_zero1_train_step pins this with out_shardings).
# The table deliberately uses a STATE-ONLY logical axis name: optimizer
# state is elementwise math, so sharding it can never split a
# reduction — but the moment a MODEL axis name (embed/heads/mlp/...)
# appears here, the same annotations would partition contraction dims
# of the traced step. graftlint's sharding-partitioned-contraction rule
# polices exactly that (ZERO1_STATE_RULES is a bit-exactness table:
# an entry naming an axis that appears in contraction position at any
# einsum/dot site in models/ or parallel/ fails `make lint`).
ZERO1_STATE_RULES: Rules = {
    "zero1_shard": "data",
}


def decode_rules(config, mesh: Mesh) -> Rules:
    """DECODE_RULES specialized to a config + mesh: a dim only shards
    over "model" when its size divides the axis (an indivisible head or
    vocab dim replicates instead of forcing GSPMD's padded sharding —
    padding is correct but wastes the ragged shard's HBM and compute)."""
    model = mesh.shape.get("model", 1)
    rules = dict(DECODE_RULES)
    if model > 1:
        for axis, size in (("heads", config.n_heads),
                           ("kv_heads", config.n_kv_heads),
                           ("mlp", config.mlp_dim),
                           ("vocab", config.vocab_size)):
            if size % model:
                rules[axis] = None
        # GQA reshape constraint: q's heads axis regroups as
        # (kv_heads, groups) inside attention, which only stays a local
        # reshape when the kv split is at least as fine as the head
        # split — otherwise replicate heads with the kv cache.
        if rules["kv_heads"] is None:
            rules["heads"] = None
    return rules


_ctx = threading.local()


@contextmanager
def axis_rules(mesh: Mesh, rules: Optional[Rules] = None):
    """Set the (mesh, rules) context under which ``constrain`` resolves
    logical axes. Train-step builders trace model code inside this context;
    model code stays mesh-agnostic (t5x ``axis_rules`` idiom)."""
    prev = getattr(_ctx, "value", None)
    _ctx.value = (mesh, rules or DEFAULT_RULES)
    try:
        yield
    finally:
        _ctx.value = prev


def constrain(x: jax.Array, logical_axes: Sequence[Optional[str]]):
    """Apply a GSPMD sharding constraint by logical axis names; no-op when
    no axis_rules context is active (single-device paths, tests).

    A mesh axis that does not divide the tensor's actual dim is dropped
    for that dim (replicate instead): jaxlib 0.4.37 rejects uneven
    shardings outright, and the decode plane traces the same constraint
    sites at many batch sizes (admission waves of 1..slots rows) — a
    2-row wave on an 8-way batch axis must replicate, not crash."""
    ctx = getattr(_ctx, "value", None)
    if ctx is None:
        return x
    mesh, rules = ctx
    spec = spec_for(logical_axes, rules)
    parts = list(spec) + [None] * (x.ndim - len(spec))
    for i, part in enumerate(parts):
        if part is None:
            continue
        axes = part if isinstance(part, tuple) else (part,)
        size = 1
        for ax in axes:
            size *= mesh.shape.get(ax, 1)
        if size and x.shape[i] % size:
            parts[i] = None
    while parts and parts[-1] is None:
        parts.pop()
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*parts)))


def current_mesh() -> Optional[Mesh]:
    ctx = getattr(_ctx, "value", None)
    return None if ctx is None else ctx[0]


def batch_sharding(mesh: Mesh, rules: Optional[Rules] = None) -> NamedSharding:
    """Sharding for (batch, length, ...) input batches."""
    return NamedSharding(mesh, spec_for(("batch", "length"), rules))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
