"""SPMD train-step builder: model + optimizer + mesh -> one compiled step.

This is the compute core of the JaxTrainer (the reference's equivalent layer
is Train's per-worker torch train loop + DDP/NCCL, ``train/torch/config.py``;
here the entire parallelism stack — DP/FSDP/TP/SP — is inside one jitted
function and XLA inserts the collectives). The builder:

1. materializes params *directly sharded* (jit init with out_shardings — no
   host-side full copy, which matters at 7B+),
2. derives optimizer-state shardings by propagation (jit of optimizer.init
   over committed-sharded params),
3. returns a donated, jitted ``step(params, opt_state, batch)`` whose body
   runs under the mesh's ``axis_rules`` so the model's ``constrain`` calls
   resolve.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import (
    ZERO1_STATE_RULES,
    Rules,
    axis_rules,
    batch_sharding,
    spec_for,
    tree_shardings,
)


def init_sharded_params(init_fn: Callable[[jax.Array], Any],
                        axes_tree: Any, mesh: Mesh, key: jax.Array,
                        rules: Optional[Rules] = None):
    """Run ``init_fn(key)`` with outputs materialized under the mesh's param
    shardings — each device only ever holds its shard."""
    shardings = tree_shardings(mesh, axes_tree, rules)
    with jax.transfer_guard("allow"):
        init = jax.jit(init_fn, out_shardings=shardings)
        return init(key)


def init_optimizer_state(optimizer: optax.GradientTransformation, params):
    """optimizer.init jitted over committed-sharded params: XLA propagates
    param shardings into mu/nu etc. (ZeRO optimizer-state sharding for free —
    the 'no separate code path' cell of SURVEY §2.4's FSDP row)."""
    return jax.jit(optimizer.init)(params)


def build_train_step(
    loss_fn: Callable[[Any, Dict[str, jax.Array]], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    rules: Optional[Rules] = None,
    extra_metrics: Optional[Callable] = None,
    accum_steps: int = 1,
    out_shardings=None,
):
    """Returns ``step(params, opt_state, batch) -> (params, opt_state,
    metrics)``, jitted with donated state. ``out_shardings`` (a
    ``(params, opt_state, metrics)`` sharding triple, None = let XLA
    propagate) is how :func:`build_zero1_train_step` pins the ZeRO-1
    layout without a second step body.

    ``accum_steps > 1`` splits the batch's leading axis into that many
    microbatches and accumulates fp32 gradients over a ``lax.scan`` before
    ONE optimizer update. The fp32->bf16 parameter cast is hoisted out of
    the microbatch loop, so both the cast and the (bandwidth-bound on TPU)
    optimizer pass amortize over ``accum_steps`` times more tokens — worth
    several MFU points on memory-limited parts (see BENCH_NOTES.md).

    On a multi-chip mesh keep ``batch_size / accum_steps`` a multiple of
    the batch-sharding mesh extent (data x fsdp), or XLA resorts to
    replicate-then-reshard on every microbatch slice."""

    def _grads_accum(params, batch):
        pbf = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32 else x, params)

        def micro(g_acc, mb):
            loss, g = jax.value_and_grad(loss_fn)(pbf, mb)
            g_acc = jax.tree.map(lambda a, b: a + b.astype(a.dtype),
                                 g_acc, g)
            return g_acc, loss

        mbs = jax.tree.map(
            lambda x: x.reshape((accum_steps, x.shape[0] // accum_steps)
                                + x.shape[1:]), batch)
        g0 = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), params)
        grads, losses = jax.lax.scan(micro, g0, mbs)
        grads = jax.tree.map(lambda g: g / accum_steps, grads)
        return losses.mean(), grads

    def step(params, opt_state, batch):
        with axis_rules(mesh, rules):
            if accum_steps > 1:
                loss, grads = _grads_accum(params, batch)
            else:
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, new_opt_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            metrics = {"loss": loss,
                       "grad_norm": optax.global_norm(grads)}
            if extra_metrics is not None:
                metrics.update(extra_metrics(new_params, batch))
        return new_params, new_opt_state, metrics

    if out_shardings is not None:
        return jax.jit(step, donate_argnums=(0, 1),
                       out_shardings=out_shardings)
    return jax.jit(step, donate_argnums=(0, 1))


# --------------------------------------------------------------- ZeRO-1
#
# Cross-replica sharded weight update ("Automatic Cross-Replica Sharding
# of Weight Update in Data-Parallel Training", PAPERS.md) expressed as
# sharding ANNOTATIONS on the optimizer state: params stay replicated
# (plain DP semantics, every replica sees the full model), while mu/nu
# (and any fp32 master copies optax keeps) shard 1/N over the data axis.
# XLA reads the annotations and compiles the weight update into
# reduce-scatter(grads) -> per-shard elementwise update -> all-gather
# (params), run ONCE per step — the update's memory AND flops drop to
# 1/N per replica with zero model-code changes. The mesh axis the state
# shards over comes from ``sharding.ZERO1_STATE_RULES`` (a rule-table
# annotation graftlint polices: a table edit that would partition a
# contraction dim of the traced step fails ``make lint``).


def zero1_state_shardings(mesh: Mesh, opt_state: Any,
                          zero1_rules: Optional[Rules] = None):
    """NamedShardings for an optimizer-state pytree: each array leaf
    shards its FIRST axis-divisible dim over the ZeRO-1 mesh axis; leaves
    with no divisible dim (scalars like adam's ``count``, tiny norms)
    replicate — jax 0.4.37 rejects uneven shardings, and a ragged shard
    would waste the padding anyway. Works on concrete arrays or
    ``jax.eval_shape`` structs.

    ``zero1_rules`` is the ZeRO-1 STATE table (default
    :data:`~ray_tpu.parallel.sharding.ZERO1_STATE_RULES`), not the
    model-axis rules table — a table without the ``zero1_shard`` key is
    rejected rather than silently replicating the state."""
    table = zero1_rules or ZERO1_STATE_RULES
    if "zero1_shard" not in table:
        raise ValueError(
            "ZeRO-1 state table has no 'zero1_shard' key — this looks "
            "like a model-axis rules table passed where the state "
            "table belongs (the state would silently replicate); pass "
            "it as rules=, and the state table as zero1_rules=")
    mesh_ax = table.get("zero1_shard")
    n = mesh.shape.get(mesh_ax, 1) if isinstance(mesh_ax, str) else 1
    replicated_sh = NamedSharding(mesh, P())

    def leaf_sharding(x):
        shape = getattr(x, "shape", ())
        if n > 1:
            for dim, size in enumerate(shape):
                if size >= n and size % n == 0:
                    return NamedSharding(
                        mesh, P(*([None] * dim + [mesh_ax])))
        return replicated_sh

    return jax.tree.map(leaf_sharding, opt_state)


def init_zero1_opt_state(optimizer: optax.GradientTransformation, params,
                         mesh: Mesh,
                         zero1_rules: Optional[Rules] = None):
    """``optimizer.init`` jitted with ZeRO-1 out_shardings: every state
    leaf materializes already sharded over the data axis — no replica
    ever holds the full optimizer state."""
    state_shape = jax.eval_shape(optimizer.init, params)
    shardings = zero1_state_shardings(mesh, state_shape, zero1_rules)
    with jax.transfer_guard("allow"):
        return jax.jit(optimizer.init, out_shardings=shardings)(params)


def build_zero1_train_step(
    loss_fn: Callable[[Any, Dict[str, jax.Array]], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    params,
    rules: Optional[Rules] = None,
    zero1_rules: Optional[Rules] = None,
    extra_metrics: Optional[Callable] = None,
    accum_steps: int = 1,
):
    """ZeRO-1 twin of :func:`build_train_step`: same step body, but the
    jit pins out_shardings — params REPLICATED (the once-per-step
    all-gather of the updated weights), optimizer state sharded per
    :func:`zero1_state_shardings`. ``params`` is only inspected for
    structure (``jax.eval_shape``); pass the live pytree.

    ``rules`` and ``zero1_rules`` are DISTINCT namespaces: ``rules`` is
    the model-axis table the step body runs under (resolving the
    model's ``constrain`` calls, like :func:`build_train_step`),
    ``zero1_rules`` is the ZeRO-1 state table (default
    ``ZERO1_STATE_RULES``). A single parameter used to feed both, so
    any non-None value silently broke one of the two uses — most
    treacherously, a model table made ``zero1_shard`` miss and the
    state replicated with no error."""
    state_shape = jax.eval_shape(optimizer.init, params)
    opt_shardings = zero1_state_shardings(mesh, state_shape, zero1_rules)
    replicated_sh = NamedSharding(mesh, P())
    param_shardings = jax.tree.map(lambda _: replicated_sh, params)
    step = build_train_step(
        loss_fn, optimizer, mesh, rules=rules,
        extra_metrics=extra_metrics, accum_steps=accum_steps,
        out_shardings=(param_shardings, opt_shardings, None))

    def traced_step(params, opt_state, batch):
        """One span per ZeRO-1 step when a trace is active (the
        params all-gather is the out_shardings pin INSIDE the jitted
        program, so the span covers update+gather as one unit —
        `ray_tpu timeline --train` renders it on the learner's row).
        Untraced callers pay one contextvar read."""
        from ray_tpu.util import tracing

        if not tracing.traced():
            return step(params, opt_state, batch)
        import time as _time

        t0 = _time.time()
        out = step(params, opt_state, batch)
        jax.block_until_ready(out[0])
        tracing.record_span("zero1:step", t0, _time.time(),
                            allgather="params", zero1=True)
        return out

    return traced_step


def per_replica_state_bytes(opt_state) -> int:
    """The WORST replica's resident optimizer-state bytes: per device,
    the sum of that device's addressable shard bytes across every state
    leaf (a replicated leaf charges its full size to every device; a
    ZeRO-1 leaf charges 1/N). The ZeRO-1 acceptance asserts this lands
    at ~1/N of the unsharded total."""
    per_device: Dict[Any, int] = {}
    for leaf in jax.tree.leaves(opt_state):
        if not hasattr(leaf, "addressable_shards"):
            continue
        for shard in leaf.addressable_shards:
            per_device[shard.device] = (per_device.get(shard.device, 0)
                                        + shard.data.nbytes)
    return max(per_device.values()) if per_device else 0


def build_eval_step(loss_fn, mesh, rules=None):
    def eval_step(params, batch):
        with axis_rules(mesh, rules):
            return loss_fn(params, batch)

    return jax.jit(eval_step)


def shard_batch(batch: Dict[str, jax.Array], mesh: Mesh,
                rules: Optional[Rules] = None):
    """Batch-shard every leaf: (batch, length) for rank >= 2 leaves, batch
    only for rank-1 leaves (labels, weights — image batches mix ranks)."""
    sh = batch_sharding(mesh, rules)
    sh1 = NamedSharding(mesh, spec_for(("batch",), rules))

    def put(x):
        return jax.device_put(x, sh1 if jnp.ndim(x) <= 1 else sh)

    return jax.tree.map(put, batch)
