"""SPMD train-step builder: model + optimizer + mesh -> one compiled step.

This is the compute core of the JaxTrainer (the reference's equivalent layer
is Train's per-worker torch train loop + DDP/NCCL, ``train/torch/config.py``;
here the entire parallelism stack — DP/FSDP/TP/SP — is inside one jitted
function and XLA inserts the collectives). The builder:

1. materializes params *directly sharded* (jit init with out_shardings — no
   host-side full copy, which matters at 7B+),
2. derives optimizer-state shardings by propagation (jit of optimizer.init
   over committed-sharded params),
3. returns a donated, jitted ``step(params, opt_state, batch)`` whose body
   runs under the mesh's ``axis_rules`` so the model's ``constrain`` calls
   resolve.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import (
    Rules,
    axis_rules,
    batch_sharding,
    spec_for,
    tree_shardings,
)


def init_sharded_params(init_fn: Callable[[jax.Array], Any],
                        axes_tree: Any, mesh: Mesh, key: jax.Array,
                        rules: Optional[Rules] = None):
    """Run ``init_fn(key)`` with outputs materialized under the mesh's param
    shardings — each device only ever holds its shard."""
    shardings = tree_shardings(mesh, axes_tree, rules)
    with jax.transfer_guard("allow"):
        init = jax.jit(init_fn, out_shardings=shardings)
        return init(key)


def init_optimizer_state(optimizer: optax.GradientTransformation, params):
    """optimizer.init jitted over committed-sharded params: XLA propagates
    param shardings into mu/nu etc. (ZeRO optimizer-state sharding for free —
    the 'no separate code path' cell of SURVEY §2.4's FSDP row)."""
    return jax.jit(optimizer.init)(params)


def build_train_step(
    loss_fn: Callable[[Any, Dict[str, jax.Array]], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    rules: Optional[Rules] = None,
    extra_metrics: Optional[Callable] = None,
    accum_steps: int = 1,
):
    """Returns ``step(params, opt_state, batch) -> (params, opt_state,
    metrics)``, jitted with donated state.

    ``accum_steps > 1`` splits the batch's leading axis into that many
    microbatches and accumulates fp32 gradients over a ``lax.scan`` before
    ONE optimizer update. The fp32->bf16 parameter cast is hoisted out of
    the microbatch loop, so both the cast and the (bandwidth-bound on TPU)
    optimizer pass amortize over ``accum_steps`` times more tokens — worth
    several MFU points on memory-limited parts (see BENCH_NOTES.md).

    On a multi-chip mesh keep ``batch_size / accum_steps`` a multiple of
    the batch-sharding mesh extent (data x fsdp), or XLA resorts to
    replicate-then-reshard on every microbatch slice."""

    def _grads_accum(params, batch):
        pbf = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32 else x, params)

        def micro(g_acc, mb):
            loss, g = jax.value_and_grad(loss_fn)(pbf, mb)
            g_acc = jax.tree.map(lambda a, b: a + b.astype(a.dtype),
                                 g_acc, g)
            return g_acc, loss

        mbs = jax.tree.map(
            lambda x: x.reshape((accum_steps, x.shape[0] // accum_steps)
                                + x.shape[1:]), batch)
        g0 = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), params)
        grads, losses = jax.lax.scan(micro, g0, mbs)
        grads = jax.tree.map(lambda g: g / accum_steps, grads)
        return losses.mean(), grads

    def step(params, opt_state, batch):
        with axis_rules(mesh, rules):
            if accum_steps > 1:
                loss, grads = _grads_accum(params, batch)
            else:
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, new_opt_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            metrics = {"loss": loss,
                       "grad_norm": optax.global_norm(grads)}
            if extra_metrics is not None:
                metrics.update(extra_metrics(new_params, batch))
        return new_params, new_opt_state, metrics

    return jax.jit(step, donate_argnums=(0, 1))


def build_eval_step(loss_fn, mesh, rules=None):
    def eval_step(params, batch):
        with axis_rules(mesh, rules):
            return loss_fn(params, batch)

    return jax.jit(eval_step)


def shard_batch(batch: Dict[str, jax.Array], mesh: Mesh,
                rules: Optional[Rules] = None):
    """Batch-shard every leaf: (batch, length) for rank >= 2 leaves, batch
    only for rank-1 leaves (labels, weights — image batches mix ranks)."""
    sh = batch_sharding(mesh, rules)
    sh1 = NamedSharding(mesh, spec_for(("batch",), rules))

    def put(x):
        return jax.device_put(x, sh1 if jnp.ndim(x) <= 1 else sh)

    return jax.tree.map(put, batch)
