"""Device meshes: the declarative backbone of every parallelism strategy.

This replaces the reference's entire tensor-plane stack (SURVEY §5.8: torch
process groups, NCCL/Gloo collective groups, Horovod) with the TPU-native
model: parallelism is *declared* as a `jax.sharding.Mesh` with named axes and
compiled by XLA into ICI/DCN collectives — the mesh is declared, not
connected. The framework's job is only to decide the mesh shape from the
slice topology and hand out shardings.

Axis convention (superset of the reference's §2.4 strategy inventory):

| axis       | strategy                 | typical collective (inserted by XLA) |
|------------|--------------------------|--------------------------------------|
| ``data``   | data parallel            | psum of grads (ICI/DCN all-reduce)   |
| ``fsdp``   | sharded data parallel    | all-gather params, reduce-scatter    |
| ``tensor`` | tensor/Megatron parallel | all-reduce of activations            |
| ``seq``    | sequence/context parallel| ppermute (ring attention)            |
| ``expert`` | expert parallel (MoE)    | all-to-all token routing             |
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "fsdp", "tensor", "seq", "expert")


@dataclass(frozen=True)
class MeshSpec:
    """A named parallelism layout. Sizes must multiply to the device count
    (a -1 entry is inferred, like a reshape).

    ``dcn_data > 1`` declares a MULTI-SLICE layout: that many data-parallel
    replicas across pod slices connected by DCN (the standard multislice
    recipe — gradient all-reduce is the only cross-slice collective, so it
    alone rides the slow network while fsdp/tensor/seq/expert stay on
    intra-slice ICI). The DCN factor folds into the ``data`` mesh axis, so
    sharding rules are unchanged: ``batch`` over ("data", "fsdp") is
    automatically slice-count x per-slice-data parallel."""

    data: int = 1
    fsdp: int = -1   # default: soak up remaining devices as sharded-DP
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    dcn_data: int = 1  # data-parallel replicas across slices (over DCN)

    def sizes(self, n_devices: int) -> Tuple[int, ...]:
        """Final per-axis sizes (dcn folded into data)."""
        if n_devices % self.dcn_data:
            raise ValueError(
                f"{n_devices} devices not divisible across "
                f"{self.dcn_data} slices")
        per_slice = self._ici_sizes(n_devices // self.dcn_data)
        return (per_slice[0] * self.dcn_data,) + per_slice[1:]

    def _ici_sizes(self, n_devices: int) -> Tuple[int, ...]:
        sizes = [self.data, self.fsdp, self.tensor, self.seq, self.expert]
        if sizes.count(-1) > 1:
            raise ValueError("at most one mesh axis may be -1")
        known = math.prod(s for s in sizes if s != -1)
        if -1 in sizes:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by {known}")
            sizes[sizes.index(-1)] = n_devices // known
        if math.prod(sizes) != n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXES, sizes))} needs {math.prod(sizes)} "
                f"devices, have {n_devices}")
        return tuple(sizes)

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        """Build the mesh over ``devices`` (default: all addressable).

        Device order: ``jax.experimental.mesh_utils`` places neighbors on ICI
        where possible; multi-slice layouts use
        ``create_hybrid_device_mesh`` so the dcn factor maps to the
        slice boundary (slowest varying). We fall back to a plain reshape on
        CPU/virtual devices (tests use an 8-device virtual CPU mesh, where
        the fallback emulates the slice split)."""
        if devices is None:
            devices = jax.devices()
        devices = np.asarray(devices)
        sizes = self.sizes(devices.size)
        if self.dcn_data > 1:
            ici = self._ici_sizes(devices.size // self.dcn_data)
            dcn = (self.dcn_data, 1, 1, 1, 1)
            on_tpu = any(getattr(d, "platform", "") == "tpu"
                         for d in devices.flat)
            try:
                from jax.experimental import mesh_utils

                dev_array = mesh_utils.create_hybrid_device_mesh(
                    ici, dcn, devices=list(devices.flat))
            except Exception:
                if on_tpu:
                    # On real hardware a hybrid-mesh failure means the spec
                    # does not match the slice topology; a silent reshape
                    # would put fsdp/tensor collectives on DCN.
                    raise
                # Virtual/CPU devices carry no slice topology: emulate the
                # slice split with dcn as the slowest-varying factor.
                dev_array = devices.reshape((self.dcn_data,) + ici).reshape(
                    sizes)
            dev_array = dev_array.reshape(sizes)
            return Mesh(dev_array, AXES)
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(
                sizes, devices=list(devices.flat))
        except Exception:
            dev_array = devices.reshape(sizes)
        return Mesh(dev_array, AXES)


def single_device_mesh() -> Mesh:
    return MeshSpec(data=1, fsdp=1).build(jax.devices()[:1])


# Serving meshes use their own 2-axis naming (SNIPPETS [1]: ``batch`` x
# ``model``): a decode replica has no optimizer state, so the train-side
# data/fsdp/tensor split collapses to "which slots" x "which shard of the
# weights". Kept separate from AXES so train and serve rule tables can't
# cross-contaminate.
DECODE_AXES = ("batch", "model")


def decode_mesh(shape: Tuple[int, int],
                devices: Optional[Sequence] = None) -> Mesh:
    """Named 2-D serving mesh: ``shape = (batch, model)`` over the first
    ``batch * model`` addressable devices (or the explicit ``devices`` a
    sub-slice reservation mapped). ICI ordering comes from
    ``mesh_utils.create_device_mesh`` on real slices; virtual/CPU devices
    fall back to a plain reshape, like :meth:`MeshSpec.build`."""
    b, m = int(shape[0]), int(shape[1])
    if b < 1 or m < 1:
        raise ValueError(f"mesh shape must be positive, got {shape}")
    if devices is None:
        devices = jax.devices()[:b * m]
    devices = np.asarray(devices)
    if devices.size != b * m:
        raise ValueError(
            f"decode mesh {b}x{m} needs {b * m} devices, have "
            f"{devices.size}")
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            (b, m), devices=list(devices.flat))
    except Exception:
        dev_array = devices.reshape((b, m))
    return Mesh(dev_array, DECODE_AXES)


# Topology presets keyed by (pod type prefix, device count) intent. These are
# starting points, not laws: the scaling-book recipe is pick mesh -> profile
# -> iterate.
def preset_for(n_devices: int, model_params: int = 0) -> MeshSpec:
    """Heuristic preset: small models pure (fsdp), big models tensor-shard
    within a host (<=8 chips share fastest ICI) and fsdp across."""
    if model_params >= 30_000_000_000 and n_devices >= 8:
        return MeshSpec(tensor=8, fsdp=-1)
    if model_params >= 6_000_000_000 and n_devices >= 4:
        return MeshSpec(tensor=4, fsdp=-1)
    return MeshSpec(fsdp=-1)
