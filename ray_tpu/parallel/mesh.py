"""Device meshes: the declarative backbone of every parallelism strategy.

This replaces the reference's entire tensor-plane stack (SURVEY §5.8: torch
process groups, NCCL/Gloo collective groups, Horovod) with the TPU-native
model: parallelism is *declared* as a `jax.sharding.Mesh` with named axes and
compiled by XLA into ICI/DCN collectives — the mesh is declared, not
connected. The framework's job is only to decide the mesh shape from the
slice topology and hand out shardings.

Axis convention (superset of the reference's §2.4 strategy inventory):

| axis       | strategy                 | typical collective (inserted by XLA) |
|------------|--------------------------|--------------------------------------|
| ``data``   | data parallel            | psum of grads (ICI/DCN all-reduce)   |
| ``fsdp``   | sharded data parallel    | all-gather params, reduce-scatter    |
| ``tensor`` | tensor/Megatron parallel | all-reduce of activations            |
| ``seq``    | sequence/context parallel| ppermute (ring attention)            |
| ``expert`` | expert parallel (MoE)    | all-to-all token routing             |
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "fsdp", "tensor", "seq", "expert")


@dataclass(frozen=True)
class MeshSpec:
    """A named parallelism layout. Sizes must multiply to the device count
    (a -1 entry is inferred, like a reshape)."""

    data: int = 1
    fsdp: int = -1   # default: soak up remaining devices as sharded-DP
    tensor: int = 1
    seq: int = 1
    expert: int = 1

    def sizes(self, n_devices: int) -> Tuple[int, ...]:
        sizes = [self.data, self.fsdp, self.tensor, self.seq, self.expert]
        if sizes.count(-1) > 1:
            raise ValueError("at most one mesh axis may be -1")
        known = math.prod(s for s in sizes if s != -1)
        if -1 in sizes:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by {known}")
            sizes[sizes.index(-1)] = n_devices // known
        if math.prod(sizes) != n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXES, sizes))} needs {math.prod(sizes)} "
                f"devices, have {n_devices}")
        return tuple(sizes)

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        """Build the mesh over ``devices`` (default: all addressable).

        Device order: ``jax.experimental.mesh_utils`` places neighbors on ICI
        where possible; we fall back to a plain reshape on CPU/virtual
        devices (tests use an 8-device virtual CPU mesh)."""
        if devices is None:
            devices = jax.devices()
        devices = np.asarray(devices)
        sizes = self.sizes(devices.size)
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(
                sizes, devices=list(devices.flat))
        except Exception:
            dev_array = devices.reshape(sizes)
        return Mesh(dev_array, AXES)


def single_device_mesh() -> Mesh:
    return MeshSpec(data=1, fsdp=1).build(jax.devices()[:1])


# Topology presets keyed by (pod type prefix, device count) intent. These are
# starting points, not laws: the scaling-book recipe is pick mesh -> profile
# -> iterate.
def preset_for(n_devices: int, model_params: int = 0) -> MeshSpec:
    """Heuristic preset: small models pure (fsdp), big models tensor-shard
    within a host (<=8 chips share fastest ICI) and fsdp across."""
    if model_params >= 30_000_000_000 and n_devices >= 8:
        return MeshSpec(tensor=8, fsdp=-1)
    if model_params >= 6_000_000_000 and n_devices >= 4:
        return MeshSpec(tensor=4, fsdp=-1)
    return MeshSpec(fsdp=-1)
