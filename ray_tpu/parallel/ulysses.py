"""Ulysses sequence parallelism: all-to-all head scattering.

The second first-class long-context strategy SURVEY §5.7 demands (next to
ring attention): with the sequence sharded over the ``seq`` mesh axis,
attention needs every query to see every key. Ulysses (DeepSpeed-Ulysses)
converts sequence-sharding into head-sharding for the attention op:

    (B, S/p, H, D) --all_to_all--> (B, S, H/p, D)   heads scattered
        full-sequence attention on H/p local heads
    (B, S, H/p, D) --all_to_all--> (B, S/p, H, D)   back to seq-sharded

Two all-to-alls ride the ICI per layer instead of ring attention's p
ppermute steps; for p <= H it moves strictly less data than an all-gather
of K/V and keeps the attention kernel itself unchanged (so it composes
with the Pallas flash kernel). Reference world: absent from the reference
itself (its role is placement; SURVEY §2.4 SP row) — this is the TPU-native
implementation.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from jax.experimental.shard_map import shard_map


def _attention_local(q, k, v, causal: bool, q_offset: int, impl: str):
    if impl == "flash" and jax.default_backend() == "tpu":
        from ray_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, q_offset=q_offset)
    from ray_tpu.ops.attention import attention

    return attention(q, k, v, causal=causal, q_offset=q_offset)


def ulysses_attention(
    q: jax.Array,  # (B, S, H, D) with S sharded over mesh axis ``seq``
    k: jax.Array,  # (B, S, Hkv, D)
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    causal: bool = True,
    impl: str = "flash",
) -> jax.Array:
    """Exact attention over a sequence-sharded input via head scattering."""
    p = mesh.shape.get(seq_axis, 1)
    if p == 1:
        return _attention_local(q, k, v, causal, 0, impl)
    n_heads, n_kv = q.shape[2], k.shape[2]
    if n_heads % p or n_kv % p:
        raise ValueError(
            f"ulysses needs heads divisible by the seq axis: "
            f"{n_heads}/{n_kv} heads over {p} shards")

    def local(q, k, v):
        # In: (B, S/p, H, D) shards. all_to_all splits the HEAD axis and
        # concatenates the SEQ axis -> (B, S, H/p, D).
        qg = jax.lax.all_to_all(q, seq_axis, split_axis=2, concat_axis=1,
                                tiled=True)
        kg = jax.lax.all_to_all(k, seq_axis, split_axis=2, concat_axis=1,
                                tiled=True)
        vg = jax.lax.all_to_all(v, seq_axis, split_axis=2, concat_axis=1,
                                tiled=True)
        out = _attention_local(qg, kg, vg, causal, 0, impl)
        # Back: split SEQ, concatenate HEADS -> (B, S/p, H, D).
        return jax.lax.all_to_all(out, seq_axis, split_axis=1,
                                  concat_axis=2, tiled=True)

    spec = P(None, seq_axis, None, None)
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)
