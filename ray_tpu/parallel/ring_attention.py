"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

First-class in this framework where the reference has nothing (SURVEY §5.7:
"absent from the reference — the TPU framework must make this first-class").
Each device holds a contiguous sequence shard of Q, K and V; K/V blocks
rotate around the ring via ``lax.ppermute`` (compiled to ICI neighbor
transfers, which is what the ring layout is *for* — every hop is one ICI
link), and partial attention results merge with the online-softmax
log-sum-exp rule. Attention memory stays O(S_local^2) per device and the
full sequence is never gathered.

Causality comes free from global position offsets: a KV block from a shard
entirely ahead of the local Q shard contributes a fully-masked block (zero
weight), so the math is exact — blocks are not skipped, keeping the loop
shape static for XLA (compute for those blocks is the price of regularity;
a later Pallas kernel can overlap it away with RDMA double-buffering).

Differentiable end-to-end: autodiff of ``ppermute`` produces the reverse
rotation in the backward pass, giving the standard ring-attention backward
schedule without custom VJP code.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ray_tpu.ops.attention import (
    attention_block_stats,
    finalize_attention,
    merge_attention_stats,
)


def ring_attention_local(q, k, v, axis_name: str = "seq",
                         causal: bool = True) -> jax.Array:
    """Per-shard ring attention body; call inside shard_map/pjit-manual.

    Shapes are per-device: q/k/v (B, S_local, H, D) with the global sequence
    laid out contiguously across the ``axis_name`` ring.
    """
    n = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_offset = rank * s_local
    q32 = q.astype(jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def block(acc, m, l, k_cur, v_cur, step):
        src = (rank - step) % n  # origin shard of the K/V block we now hold
        kv_offset = src * s_local
        b_acc, b_m, b_l = attention_block_stats(
            q32, k_cur, v_cur, causal, q_offset, kv_offset)
        return merge_attention_stats(acc, m, l, b_acc, b_m, b_l)

    def body(i, carry):
        acc, m, l, k_cur, v_cur = carry
        # Rotate first (steps 1..n-1), so the final block is not followed by
        # a wasted pair of full-shard ICI transfers.
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        acc, m, l = block(acc, m, l, k_cur, v_cur, i)
        return acc, m, l, k_cur, v_cur

    b, _, h_q, d = q.shape
    acc0 = jnp.zeros((b, h_q, s_local, d), jnp.float32)
    m0 = jnp.full((b, h_q, s_local), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h_q, s_local), jnp.float32)
    acc0, m0, l0 = block(acc0, m0, l0, k, v, 0)  # local block, no transfer
    acc, m, l, _, _ = jax.lax.fori_loop(
        1, n, body, (acc0, m0, l0, k, v))
    return finalize_attention(acc, l, q.dtype)


def ring_attention(q, k, v, mesh: Mesh, causal: bool = True,
                   axis_name: str = "seq",
                   batch_axes=("data", "fsdp"),
                   head_axis: Optional[str] = "tensor") -> jax.Array:
    """shard_map wrapper: global (B, S, H, D) arrays sharded batch x seq x
    heads; returns attention output with the same sharding."""
    spec = P(batch_axes, axis_name, head_axis, None)
    fn = shard_map(
        partial(ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
