"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

First-class in this framework where the reference has nothing (SURVEY §5.7:
"absent from the reference — the TPU framework must make this first-class").
Each device holds a contiguous sequence shard of Q, K and V; K/V blocks
rotate around the ring via ``lax.ppermute`` (compiled to ICI neighbor
transfers, which is what the ring layout is *for* — every hop is one ICI
link), and partial attention results merge with the online-softmax
log-sum-exp rule. Attention memory stays O(S_local^2) per device and the
full sequence is never gathered.

Causality comes free from global position offsets: a KV block from a shard
entirely ahead of the local Q shard contributes a fully-masked block (zero
weight), so the math is exact — blocks are not skipped, keeping the loop
shape static for XLA (compute for those blocks is the price of regularity;
a later Pallas kernel can overlap it away with RDMA double-buffering).

Differentiable end-to-end: autodiff of ``ppermute`` produces the reverse
rotation in the backward pass, giving the standard ring-attention backward
schedule without custom VJP code.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 moved shard_map to the top level
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ray_tpu.ops.attention import (
    attention_block_stats,
    finalize_attention,
    merge_attention_stats,
)


def _axis_size(axis_name: str) -> int:
    """Static ring size inside shard_map. ``jax.lax.axis_size`` only
    exists on newer jax; on older versions ``psum(1, axis)`` of a Python
    literal constant-folds to a static int under shard_map, which is what
    the ring's ``range(n)``/permutation construction needs."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def ring_attention_local(q, k, v, axis_name: str = "seq",
                         causal: bool = True) -> jax.Array:
    """Per-shard ring attention body; call inside shard_map/pjit-manual.

    Shapes are per-device: q/k/v (B, S_local, H, D) with the global sequence
    laid out contiguously across the ``axis_name`` ring.
    """
    n = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_offset = rank * s_local
    q32 = q.astype(jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def block(acc, m, l, k_cur, v_cur, step):
        src = (rank - step) % n  # origin shard of the K/V block we now hold
        kv_offset = src * s_local
        b_acc, b_m, b_l = attention_block_stats(
            q32, k_cur, v_cur, causal, q_offset, kv_offset)
        return merge_attention_stats(acc, m, l, b_acc, b_m, b_l)

    def body(i, carry):
        acc, m, l, k_cur, v_cur = carry
        # Rotate first (steps 1..n-1), so the final block is not followed by
        # a wasted pair of full-shard ICI transfers.
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        acc, m, l = block(acc, m, l, k_cur, v_cur, i)
        return acc, m, l, k_cur, v_cur

    b, _, h_q, d = q.shape
    acc0 = jnp.zeros((b, h_q, s_local, d), jnp.float32)
    m0 = jnp.full((b, h_q, s_local), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h_q, s_local), jnp.float32)
    acc0, m0, l0 = block(acc0, m0, l0, k, v, 0)  # local block, no transfer
    acc, m, l, _, _ = jax.lax.fori_loop(
        1, n, body, (acc0, m0, l0, k, v))
    return finalize_attention(acc, l, q.dtype)


def _merge_partial(o1, lse1, o2, lse2):
    """Merge two normalized partial attention results by their
    log-sum-exps (blockwise-attention merge rule). Rows dead in both
    partials stay zero."""
    m = jnp.maximum(jnp.maximum(lse1, lse2), -1e30 / 2)
    w1 = jnp.exp(lse1 - m)[..., None]
    w2 = jnp.exp(lse2 - m)[..., None]
    tot = w1 + w2
    o = jnp.where(tot == 0.0, 0.0, (o1 * w1 + o2 * w2) / jnp.where(
        tot == 0.0, 1.0, tot))
    lse = jnp.where(tot[..., 0] == 0.0, -1e30, m + jnp.log(
        jnp.where(tot[..., 0] == 0.0, 1.0, tot[..., 0])))
    return o, lse


def ring_flash_attention_local(q, k, v, axis_name: str = "seq",
                               causal: bool = True,
                               block_q: int = 256,
                               block_k: int = 256) -> jax.Array:
    """Ring attention whose per-hop block compute is the fused Pallas flash
    kernel (``flash_attention_stats``): each hop produces a normalized
    partial (out, lse) for the K/V shard currently held, merged across hops
    with the online-softmax rule. The ``ppermute`` rotation is issued
    before the hop's kernel, so XLA overlaps the ICI transfer of hop i+1
    with the flash compute of hop i (SURVEY §5.7's comm/compute overlap).

    Per-device shapes: q/k/v (B, S_local, H, D), global sequence laid out
    contiguously around the ring. Differentiable: the flash VJP accepts an
    lse cotangent, and ppermute autodiff reverses the rotation.
    """
    from ray_tpu.ops.flash_attention import flash_attention_stats

    n = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = d ** -0.5
    bq = min(block_q, s_local)
    bk = min(block_k, s_local)
    if s_local % bq or s_local % bk:
        raise ValueError(
            f"per-device sequence shard {s_local} must divide flash blocks "
            f"({bq}, {bk}); pick block sizes that divide S/seq_parallelism")

    # Lane-align head_dim for the kernel (exact: zero-pad).
    d_pad = (-d) % 128
    if d_pad:
        pad = [(0, 0), (0, 0), (0, 0), (0, d_pad)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    d_full = d + d_pad

    # (B, S, H, D) -> (B, H, S, D) once for the whole ring; the rotation
    # ppermutes the transposed K/V directly (layout-agnostic), so no
    # per-hop re-transpose copies.
    qt = q.transpose(0, 2, 1, 3)
    kt0 = k.transpose(0, 2, 1, 3)
    vt0 = v.transpose(0, 2, 1, 3)

    def hop(step, kt, vt):
        """One ring hop. With a contiguous sequence layout the causal mask
        is all-or-nothing at shard granularity for every hop but the local
        one (step 0): kv shard src=(rank-step)%n is fully visible iff
        src < rank, fully masked iff src > rank. The Pallas kernel's
        q_offset must be static, and this decomposition keeps it so — and
        lets lax.cond SKIP masked hops' compute outright (the XLA path pays
        for them; here only the rotation cost remains)."""
        if not causal:
            return flash_attention_stats(qt, kt, vt, scale, False, None, 0,
                                         bq, bk)
        if step == 0:
            return flash_attention_stats(qt, kt, vt, scale, True, None, 0,
                                         bq, bk)

        def full(ops):
            kt_, vt_ = ops
            return flash_attention_stats(qt, kt_, vt_, scale, False, None,
                                         0, bq, bk)

        def dead(ops):
            return (jnp.zeros((b, h, s_local, d_full), q.dtype),
                    jnp.full((b, h, s_local), -1e30, jnp.float32))

        return jax.lax.cond(rank >= step, full, dead, (kt, vt))

    perm = [(j, (j + 1) % n) for j in range(n)]
    o, lse = hop(0, kt0, vt0)
    o = o.astype(jnp.float32)
    k_cur, v_cur = kt0, vt0
    for step in range(1, n):
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        o_h, lse_h = hop(step, k_cur, v_cur)
        o, lse = _merge_partial(o, lse, o_h.astype(jnp.float32), lse_h)
    if d_pad:
        o = o[..., :d]
    return o.astype(q.dtype).transpose(0, 2, 1, 3)


def ring_attention(q, k, v, mesh: Mesh, causal: bool = True,
                   axis_name: str = "seq",
                   batch_axes=("data", "fsdp"),
                   head_axis: Optional[str] = "tensor",
                   impl: str = "xla") -> jax.Array:
    """shard_map wrapper: global (B, S, H, D) arrays sharded batch x seq x
    heads; returns attention output with the same sharding. ``impl="flash"``
    runs each hop through the fused Pallas kernel (tile-skipped causal
    masking + ICI/compute overlap); ``"xla"`` is the portable path."""
    spec = P(batch_axes, axis_name, head_axis, None)
    local = (ring_flash_attention_local if impl == "flash"
             else ring_attention_local)
    kwargs = {}
    if impl == "flash":
        # pallas_call inside shard_map can't declare varying-mesh-axes
        # metadata; skip the replication check for the kernel path. The
        # parameter is check_vma on jax>=0.8's top-level shard_map and
        # check_rep on the older experimental one.
        import inspect as _inspect

        params = _inspect.signature(shard_map).parameters
        kwargs["check_vma" if "check_vma" in params else "check_rep"] = False
    fn = shard_map(
        partial(local, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **kwargs,
    )
    return fn(q, k, v)
