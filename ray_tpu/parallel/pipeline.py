"""Pipeline parallelism: model-stage splitting for compiled actor pipelines.

SURVEY §2.4 PP row: the reference has no native PP — its compiled DAGs
(``dag/compiled_dag_node.py:389``) are the intended substrate. Here the
substrate exists (``ray_tpu.dag`` compiled stage pipelines with direct
actor-to-actor pushes over the shm store), and this module supplies the
model half: split a stacked-layer transformer's params into contiguous
stage slices with pure, jittable per-stage functions. Stage actors each
jit THEIR slice only (intra-stage parallelism still comes from the mesh;
PP composes on top as host-level microbatch pipelining — the GPipe
schedule emerges from the DAG's bounded in-flight window).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def stage_boundaries(n_layers: int, n_stages: int) -> List[Tuple[int, int]]:
    """Contiguous [start, end) layer ranges, balanced like np.array_split."""
    sizes = [len(part) for part in np.array_split(np.arange(n_layers),
                                                  n_stages)]
    bounds, start = [], 0
    for size in sizes:
        bounds.append((start, start + size))
        start += size
    return bounds


def llama_stage_fn(config, first: bool, last: bool) -> Callable:
    """Pure jittable fn for one Llama pipeline stage: ``fn(stage_params,
    x)``. Stage 0 takes token ids and embeds; middle stages map hidden
    states; the last stage adds final norm + LM head (fp32 logits)."""
    from ray_tpu.models.llama import (
        _decoder_layer,
        _embed_matmul,
        rms_norm,
        rope_frequencies,
    )

    c = config

    def stage_fn(p, x):
        if first:
            if c.embed_via_matmul:
                h = _embed_matmul(p["tok_embed"].astype(c.dtype), x,
                                  chunk=c.embed_chunk)
            else:
                h = p["tok_embed"].astype(c.dtype)[x]
        else:
            h = x.astype(c.dtype)
        cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)

        def body(carry, layer):
            y, _ = _decoder_layer(c, carry, layer, cos, sin, 0)
            return y, None

        if c.remat:
            # Same remat policy as hidden_states (shared helper): without
            # it, training through a stage materializes every per-layer
            # activation — OOM at exactly the sizes PP exists for.
            from ray_tpu.models.llama import remat_wrap

            body = remat_wrap(body, c)
        h, _ = jax.lax.scan(body, h, p["layers"])
        if last:
            h = rms_norm(h, p["final_norm"], c.norm_eps)
            return jnp.einsum("bse,ev->bsv", h,
                              p["lm_head"].astype(c.dtype),
                              preferred_element_type=jnp.float32)
        return h

    return stage_fn


def split_llama_stages(params: Dict[str, Any], config,
                       n_stages: int) -> List[Tuple[Dict[str, Any],
                                                    Callable]]:
    """Split Llama params into ``n_stages`` contiguous-layer pipeline
    stages (Megatron/GPipe layout). Returns [(stage_params, stage_fn)];
    each fn is pure and jittable in isolation — exactly what a DAG
    ``_PipeStage`` actor hosts."""
    bounds = stage_boundaries(config.n_layers, n_stages)
    stages: List[Tuple[Dict[str, Any], Callable]] = []
    for idx, (start, end) in enumerate(bounds):
        first, last = idx == 0, idx == n_stages - 1
        stage_params: Dict[str, Any] = {
            "layers": jax.tree.map(lambda x: x[start:end],
                                   params["layers"])}
        if first:
            stage_params["tok_embed"] = params["tok_embed"]
        if last:
            stage_params["final_norm"] = params["final_norm"]
            stage_params["lm_head"] = params["lm_head"]
        stages.append((stage_params, llama_stage_fn(config, first, last)))
    return stages


# ------------------------------------------------------ stage training
#
# The MPMD training half ("Scaling Deep Learning Training with MPMD
# Pipeline Parallelism", PAPERS.md): each stage owns its slice's
# forward AND backward as two pure jittable programs. The residual a
# stage keeps between its forward and backward is its INPUT activation
# (the backward recomputes the stage forward inside ``jax.vjp`` — the
# same activation-recompute schedule ``config.remat`` already applies
# within a stage, lifted to stage granularity), so nothing traced ever
# crosses a process boundary: activations and gradients move as arrays,
# residual stashes stay stage-local, and 1F1B's memory bound is
# ``window`` stashed inputs per stage instead of every layer's
# activations.


def llama_stage_loss_fn(config, first: bool) -> Callable:
    """Last-stage head: ``fn(stage_params, x, targets) -> scalar loss``
    — the stage forward's fp32 logits fed through the same next-token
    CE math as ``llama.loss_fn``'s unchunked path (identical ops, so a
    1-stage pipeline is bit-exact vs the single-process loss)."""
    base = llama_stage_fn(config, first=first, last=True)

    def fn(p, x, targets):
        logits = base(p, x)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, targets[..., None],
                                   axis=-1)[..., 0]
        return jnp.mean(logz - gold)

    return fn


def make_stage_train_fns(config, stage_index: int,
                         n_stages: int) -> Tuple[Callable, Callable]:
    """``(fwd, bwd)`` pure jittable programs for one training stage.

    Non-last stage: ``fwd(p, x) -> out`` and ``bwd(p, x, g_out) ->
    (g_params, g_x)``. Last stage: ``fwd(p, x, targets) -> loss`` and
    ``bwd(p, x, targets) -> (loss, g_params, g_x)`` (cotangent 1.0 on
    the scalar loss — exactly ``value_and_grad``'s pullback, so the
    degenerate 1-stage pipeline reproduces the single-process step
    bit-for-bit). ``x`` is token ids on stage 0, hidden states
    elsewhere; ``g_x`` on stage 0 is None (token ids have no
    cotangent). The backward takes the stage INPUT as its residual and
    recomputes the forward inside ``jax.vjp``."""
    first, last = stage_index == 0, stage_index == n_stages - 1

    if last:
        loss_fn = llama_stage_loss_fn(config, first)

        def fwd_last(p, x, targets):
            return loss_fn(p, x, targets)

        def bwd_last(p, x, targets):
            one = jnp.ones((), jnp.float32)
            if first:
                loss, pullback = jax.vjp(
                    lambda pp: loss_fn(pp, x, targets), p)
                (g_params,) = pullback(one)
                return loss, g_params, None
            loss, pullback = jax.vjp(
                lambda pp, xx: loss_fn(pp, xx, targets), p, x)
            g_params, g_x = pullback(one)
            return loss, g_params, g_x

        return fwd_last, bwd_last

    stage_fn = llama_stage_fn(config, first, last=False)

    def bwd(p, x, g_out):
        if first:
            _out, pullback = jax.vjp(lambda pp: stage_fn(pp, x), p)
            (g_params,) = pullback(g_out)
            return g_params, None
        _out, pullback = jax.vjp(stage_fn, p, x)
        g_params, g_x = pullback(g_out)
        return g_params, g_x

    return stage_fn, bwd


def make_stage_worker(config, stage_index: int, n_stages: int,
                      stage_params: Dict[str, Any]) -> Callable:
    """A host-callable closure for one pipeline stage, jitted lazily in
    the hosting actor process — hand this to a DAG stage. numpy in/out so
    microbatch payloads ride the object store between stage actors."""
    state: Dict[str, Any] = {"params": stage_params}

    def call(x):
        if "jitted" not in state:
            import functools

            fn = llama_stage_fn(config, stage_index == 0,
                                stage_index == n_stages - 1)
            device_params = jax.tree.map(jnp.asarray, state["params"])
            state["params"] = None  # free the host copy of the weights
            state["jitted"] = jax.jit(functools.partial(fn, device_params))
        return np.asarray(state["jitted"](jnp.asarray(x)))

    return call
