"""Runtime environments: per-task/actor env vars + working_dir packages.

Analogue of the reference's runtime-env subsystem
(``_private/runtime_env/agent/runtime_env_agent.py:162`` builds envs on
each node; ``packaging.py`` ships working_dir zips through the GCS KV).
The supported spec keys:

* ``env_vars``: dict merged into the worker's environment at fork.
* ``working_dir``: local path (same-host clusters) or ``kv://<key>`` from
  :func:`upload_working_dir` — extracted once per node per env hash, set
  as the worker's cwd and prepended to ``PYTHONPATH``.

Workers are pooled per runtime-env hash (reference: worker_pool.h's
runtime_env_hash matching), so repeated tasks with the same env reuse
their workers.
"""

from __future__ import annotations

import io
import os
import zipfile
from typing import Any, Dict

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
_MAX_PACKAGE_BYTES = 100 * 1024 * 1024


def package_working_dir(path: str) -> bytes:
    """Zip a working directory (reference: packaging.py's package zips)."""
    buf = io.BytesIO()
    root = os.path.abspath(path)
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_DIRS]
            for fname in filenames:
                full = os.path.join(dirpath, fname)
                total += os.path.getsize(full)
                if total > _MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"working_dir {path} exceeds "
                        f"{_MAX_PACKAGE_BYTES >> 20} MiB")
                zf.write(full, os.path.relpath(full, root))
    return buf.getvalue()


def upload_working_dir(path: str) -> str:
    """Package + upload a working dir to the cluster KV; returns the
    ``kv://`` URI to put in ``runtime_env['working_dir']``."""
    import hashlib

    from ray_tpu.core.runtime import get_core_worker

    blob = package_working_dir(path)
    key = f"__pkg__/{hashlib.sha1(blob).hexdigest()[:20]}.zip"
    get_core_worker().controller.call("kv_put", key, blob)
    return f"kv://{key}"


def materialize_working_dir(spec: str, controller_client) -> str:
    """Resolve a working_dir spec to a local directory: plain paths pass
    through; ``kv://`` packages are fetched from the controller KV and
    extracted once per content hash (used by the worker pool AND job
    supervisors)."""
    if not str(spec).startswith("kv://"):
        return str(spec)
    import hashlib

    key = str(spec)[len("kv://"):]
    dest = os.path.join("/tmp/ray_tpu_envs",
                        hashlib.sha1(key.encode()).hexdigest()[:16])
    marker = os.path.join(dest, ".ready")
    if not os.path.exists(marker):
        blob = controller_client.call("kv_get", key)
        if blob is None:
            raise RuntimeError(f"working_dir package {key} not in KV")
        os.makedirs(dest, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            zf.extractall(dest)
        with open(marker, "w") as f:
            f.write("ok")
    return dest


def normalize(runtime_env: Dict[str, Any]) -> Dict[str, Any]:
    """Validate + normalize a runtime_env spec (uploads local working_dir
    automatically when the cluster spans hosts is the caller's choice —
    pass a kv:// URI for that)."""
    out: Dict[str, Any] = {}
    env_vars = runtime_env.get("env_vars")
    if env_vars:
        out["env_vars"] = {str(k): str(v) for k, v in env_vars.items()}
    wd = runtime_env.get("working_dir")
    if wd:
        out["working_dir"] = str(wd)
    unknown = set(runtime_env) - {"env_vars", "working_dir"}
    if unknown:
        raise ValueError(f"unsupported runtime_env keys: {sorted(unknown)} "
                         "(supported: env_vars, working_dir)")
    return out
