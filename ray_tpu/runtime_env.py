"""Runtime environments: per-task/actor isolated Python environments.

Analogue of the reference's runtime-env subsystem
(``_private/runtime_env/agent/runtime_env_agent.py:162`` builds envs on
each node; ``packaging.py`` ships working_dir/py_modules zips through the
GCS KV; ``runtime_env/pip.py`` builds per-env virtualenvs). The supported
spec keys:

* ``env_vars``: dict merged into the worker's environment at fork.
* ``working_dir``: local path (same-host clusters) or ``kv://<key>`` from
  :func:`upload_working_dir` — extracted once per node per env hash, set
  as the worker's cwd and prepended to ``PYTHONPATH``.
* ``py_modules``: list of module/package paths or ``kv://`` zips from
  :func:`upload_py_module` — each lands on the worker's ``PYTHONPATH``.
* ``pip``: list of requirement strings (or local wheel paths). Built into
  a per-hash virtualenv on each node (``--system-site-packages`` so jax &
  friends stay visible — the TPU stack must not be reinstalled per env),
  cached across leases; the worker forks from the venv's interpreter.
  Build failures surface at lease time as the task's error (reference:
  ``pip.py`` + the agent's CreateRuntimeEnv reply).
* ``image_uri``: container-image seam (reference:
  ``runtime_env/image_uri.py``). On hosts without a container runtime the
  only backing is ``dir://<path>`` — a pre-unpacked image root used as
  the worker's cwd; ``docker://`` URIs fail the lease with a clear error.
  Third parties add further isolation backends via
  :func:`register_plugin` (reference: ``runtime_env/plugin.py``).

Workers are pooled per runtime-env hash (reference: worker_pool.h's
runtime_env_hash matching), so repeated tasks with the same env reuse
their workers.

**Cache GC** (reference: the agent's URI reference counting + cache
eviction in ``runtime_env/plugin.py``): every materialized dir under
``ENV_ROOT`` is LRU-tracked via its ``.ready`` marker's mtime;
:func:`gc_envs` evicts past a size budget, skipping dirs pinned by live
workers. The node supervisor runs it periodically
(``runtime_env_cache_bytes``).
"""

from __future__ import annotations

import io
import os
import subprocess
import sys
import threading
import zipfile
from typing import Any, Dict, List, Optional

ENV_ROOT = "/tmp/ray_tpu_envs"


class RuntimeEnvBuildError(RuntimeError):
    """Deterministic env-build failure (bad pip requirement, missing
    image root, …): leases fail FAST instead of retrying until the lease
    deadline — the same spec will fail the same way on every node."""

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
_MAX_PACKAGE_BYTES = 100 * 1024 * 1024


def package_working_dir(path: str, arcname_prefix: str = "") -> bytes:
    """Zip a directory (reference: packaging.py's package zips), size-capped.
    ``arcname_prefix`` nests the content under one directory inside the
    archive (py_modules packages zip under their own name)."""
    buf = io.BytesIO()
    root = os.path.abspath(path)
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_DIRS]
            for fname in filenames:
                full = os.path.join(dirpath, fname)
                total += os.path.getsize(full)
                if total > _MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"package {path} exceeds "
                        f"{_MAX_PACKAGE_BYTES >> 20} MiB")
                zf.write(full, os.path.join(
                    arcname_prefix, os.path.relpath(full, root)))
    return buf.getvalue()


def _upload_blob(blob: bytes) -> str:
    import hashlib

    from ray_tpu.core.runtime import get_core_worker

    key = f"__pkg__/{hashlib.sha1(blob).hexdigest()[:20]}.zip"
    get_core_worker().controller.call("kv_put", key, blob)
    return f"kv://{key}"


def upload_working_dir(path: str) -> str:
    """Package + upload a working dir to the cluster KV; returns the
    ``kv://`` URI to put in ``runtime_env['working_dir']``."""
    return _upload_blob(package_working_dir(path))


def materialize_working_dir(spec: str, controller_client) -> str:
    """Resolve a working_dir spec to a local directory: plain paths pass
    through; ``kv://`` packages are fetched from the controller KV and
    extracted once per content hash (used by the worker pool AND job
    supervisors)."""
    if not str(spec).startswith("kv://"):
        return str(spec)
    import hashlib

    key = str(spec)[len("kv://"):]
    dest = os.path.join(ENV_ROOT,
                        hashlib.sha1(key.encode()).hexdigest()[:16])
    marker = os.path.join(dest, ".ready")
    if not os.path.exists(marker):
        blob = controller_client.call("kv_get", key)
        if blob is None:
            raise RuntimeEnvBuildError(
                f"working_dir package {key} not in KV")
        os.makedirs(dest, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            zf.extractall(dest)
        with open(marker, "w") as f:
            f.write("ok")
    return dest


def upload_py_module(path: str) -> str:
    """Package one module/package (zipped UNDER its own name, so the
    extraction dir is a valid sys.path entry) and upload to the KV; returns
    the ``kv://`` URI for ``runtime_env['py_modules']`` (reference:
    packaging.py py_modules upload)."""
    root = os.path.abspath(path)
    name = os.path.basename(root.rstrip("/"))
    if os.path.isfile(root):
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.write(root, name)
        return _upload_blob(buf.getvalue())
    return _upload_blob(package_working_dir(root, arcname_prefix=name))


def materialize_py_module(spec: str, controller_client) -> str:
    """Resolve one py_modules entry to a sys.path directory: ``kv://``
    zips extract (cached per content) and the extraction dir is the path
    entry; plain paths contribute their parent directory."""
    if str(spec).startswith("kv://"):
        return materialize_working_dir(spec, controller_client)
    return os.path.dirname(os.path.abspath(str(spec)))


# ----------------------------------------------------------- pip / venv

_pip_lock = threading.Lock()


def pip_env_dir(pip: List[str]) -> str:
    import hashlib
    import json

    key = hashlib.sha1(
        json.dumps(list(pip), sort_keys=True).encode()).hexdigest()[:16]
    return os.path.join(ENV_ROOT, f"venv-{key}")


def ensure_pip_env(pip: List[str]) -> str:
    """Build (once, cached per requirement-list hash) a virtualenv with the
    requested packages; returns its python executable. The venv sees the
    base interpreter's site-packages (--system-site-packages), so the
    heavyweight TPU stack is inherited, not reinstalled (reference:
    runtime_env/pip.py builds a venv per env and caches by URI hash)."""
    dest = pip_env_dir(pip)
    python = os.path.join(dest, "bin", "python")
    marker = os.path.join(dest, ".ready")
    if os.path.exists(marker):
        return python
    with _pip_lock:  # serialize builds in this node process
        if os.path.exists(marker):
            return python
        build = f"{dest}.build-{os.getpid()}"
        try:
            # Building the venv under _pip_lock is the point of the
            # lock: concurrent builds of the same env would thrash pip's
            # cache and race the final rename; waiters get the marker
            # fast-path the moment the first build lands.
            # graftlint: disable=lock-held-blocking
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 build],
                check=True, capture_output=True, text=True, timeout=300)
            # graftlint: disable=lock-held-blocking
            proc = subprocess.run(
                [os.path.join(build, "bin", "python"), "-m", "pip",
                 "install", "--no-input", *pip],
                capture_output=True, text=True, timeout=600)
            if proc.returncode != 0:
                # Deterministic for the spec in the common case (bad
                # requirement); genuinely-transient index trouble is rare
                # on TPU pods and recoverable at the task-retry layer.
                raise RuntimeEnvBuildError(
                    f"pip install {pip} failed: "
                    f"{(proc.stderr or proc.stdout)[-800:]}")
            with open(os.path.join(build, ".ready"), "w") as f:
                f.write("ok")
            try:
                os.rename(build, dest)
            except OSError:
                if not os.path.exists(marker):  # lost a cross-process race
                    raise
        finally:
            import shutil

            # graftlint: disable=lock-held-blocking  (cleanup of the
            # build dir belongs to the same critical section)
            shutil.rmtree(build, ignore_errors=True)
    return python


# --------------------------------------------------------------- plugins


class RuntimeEnvPlugin:
    """Isolation-backend seam (reference: ``runtime_env/plugin.py``'s
    RuntimeEnvPlugin + ``image_uri.py``). A plugin owns one spec key:
    ``validate`` runs at submission time (driver side), ``build`` at
    lease time on the worker's node, mutating the build output in place
    (set ``python`` for a different interpreter, ``cwd`` for a rooted
    filesystem, extend ``pythonpath``/``env_vars``). Build failures
    become the lease's error."""

    name: str = ""

    def validate(self, value: Any) -> Any:
        return value

    def build(self, value: Any, controller_client,
              out: Dict[str, Any]) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class ImageURIPlugin(RuntimeEnvPlugin):
    """Container-image seam. Backings:

    * ``dir://<path>`` — a pre-unpacked image root (the only backing on
      hosts without a container runtime, like this box): becomes the
      worker's cwd, and its ``site-packages`` (if present) joins
      PYTHONPATH.
    * anything else (``docker://…``) — fails the lease with a clear
      error until a container runtime backend is registered.
    """

    name = "image_uri"

    def validate(self, value: Any) -> str:
        value = str(value)
        if "://" not in value:
            raise ValueError(
                "runtime_env['image_uri'] must be a URI (dir://<path> on "
                "container-less hosts, docker://<image> with a container "
                "runtime)")
        return value

    def build(self, value: Any, controller_client,
              out: Dict[str, Any]) -> None:
        uri = str(value)
        if uri.startswith("dir://"):
            root = uri[len("dir://"):]
            if not os.path.isdir(root):
                raise RuntimeEnvBuildError(
                    f"image root {root} does not exist")
            touch_env_dir(root)
            out["cwd"] = root
            site = os.path.join(root, "site-packages")
            if os.path.isdir(site):
                out["pythonpath"].append(site)
            out["env_vars"].setdefault("RAY_TPU_IMAGE_URI", uri)
            return
        raise RuntimeEnvBuildError(
            f"no container runtime available for {uri!r} on this host "
            f"(supported here: dir://<unpacked-image-root>)")


_plugins: Dict[str, RuntimeEnvPlugin] = {}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name:
        raise ValueError("plugin needs a name (its runtime_env key)")
    _plugins[plugin.name] = plugin


register_plugin(ImageURIPlugin())


# ------------------------------------------------------------------- GC


def touch_env_dir(path: str) -> None:
    """Mark an env dir as recently used (LRU clock for gc_envs)."""
    marker = os.path.join(path, ".ready")
    try:
        os.utime(marker if os.path.exists(marker) else path)
    except OSError:
        pass


def pin_env_dir(path: str, worker_id_hex: str, pid: int) -> None:
    """Record a live-process pin inside the env dir. Pins are HOST-global
    (ENV_ROOT is shared by every node on the host, and by every test
    session): GC honors any pin whose pid is still alive, so one node's
    eviction can never delete another node's live worker's env."""
    d = os.path.join(path, ".pins")
    try:
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, worker_id_hex), "w") as f:
            f.write(str(pid))
    except OSError:
        pass


def unpin_env_dir(path: str, worker_id_hex: str) -> None:
    try:
        os.unlink(os.path.join(path, ".pins", worker_id_hex))
    except OSError:
        pass


def _has_live_pin(path: str) -> bool:
    pins = os.path.join(path, ".pins")
    try:
        names = os.listdir(pins)
    except OSError:
        return False
    for name in names:
        try:
            with open(os.path.join(pins, name)) as f:
                pid = int(f.read().strip() or 0)
        except (OSError, ValueError):
            continue
        if pid <= 0:
            continue
        try:
            os.kill(pid, 0)  # alive (or zombie) => pinned
            return True
        except OSError:
            # Dead owner: clear the stale pin.
            try:
                os.unlink(os.path.join(pins, name))
            except OSError:
                pass
    return False


def _dir_bytes(path: str) -> int:
    total = 0
    for dirpath, _dirnames, filenames in os.walk(path):
        for f in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, f))
            except OSError:
                pass
    return total


def gc_envs(budget_bytes: int, in_use: Optional[set] = None,
            root: str = ENV_ROOT, min_age_s: float = 300.0) -> List[str]:
    """Evict least-recently-used env dirs until the cache fits the
    budget. Never touched: dirs in ``in_use``, dirs with a live pid pin
    (``pin_env_dir`` — covers OTHER nodes' workers on this shared host),
    dirs younger than ``min_age_s`` (closes the build-to-fork window and
    prevents evict-the-freshest thrash when pinned dirs alone exceed the
    budget), and half-built dirs (no ``.ready``). Returns the evicted
    paths (reference: the agent's URI cache eviction,
    runtime_env/plugin.py — without GC /tmp/ray_tpu_envs grows forever)."""
    import shutil
    import time as _time

    in_use = {os.path.abspath(p) for p in (in_use or set())}
    entries = []
    try:
        names = os.listdir(root)
    except OSError:
        return []
    now = _time.time()
    for name in names:
        path = os.path.abspath(os.path.join(root, name))
        marker = os.path.join(path, ".ready")
        try:
            if not os.path.isdir(path) or not os.path.exists(marker):
                continue  # half-built or foreign: leave it alone
            mtime = os.path.getmtime(marker)
            size = _dir_bytes(path)
        except OSError:
            continue  # vanished mid-scan (concurrent GC): skip
        entries.append((mtime, path, size))
    total = sum(size for _m, _p, size in entries)
    evicted: List[str] = []
    for mtime, path, size in sorted(entries):  # oldest first
        if total <= budget_bytes:
            break
        if path in in_use or now - mtime < min_age_s:
            continue
        if _has_live_pin(path):
            continue
        shutil.rmtree(path, ignore_errors=True)
        evicted.append(path)
        total -= size
    return evicted


def build_env(runtime_env: Dict[str, Any],
              controller_client) -> Dict[str, Any]:
    """Materialize a full runtime env on this node. Returns
    ``{python, pythonpath, cwd, env_vars, env_dirs}`` for the worker fork
    (``env_dirs`` = cache dirs the worker now pins against GC); raises on
    build failure (the node surfaces it in the lease reply — reference:
    the raylet failing a lease when the agent's CreateRuntimeEnv errors)."""
    out: Dict[str, Any] = {
        "python": None,
        "pythonpath": [],
        "cwd": None,
        "env_vars": {str(k): str(v) for k, v in
                     (runtime_env.get("env_vars") or {}).items()},
        "env_dirs": [],
    }
    try:
        wd = runtime_env.get("working_dir")
        if wd:
            out["cwd"] = materialize_working_dir(wd, controller_client)
            out["pythonpath"].append(out["cwd"])
            touch_env_dir(out["cwd"])
            out["env_dirs"].append(out["cwd"])
        for mod in runtime_env.get("py_modules") or []:
            entry = materialize_py_module(mod, controller_client)
            out["pythonpath"].append(entry)
            touch_env_dir(entry)
            out["env_dirs"].append(entry)
        pip = runtime_env.get("pip")
        if pip:
            out["python"] = ensure_pip_env(list(pip))
            venv_dir = os.path.dirname(os.path.dirname(out["python"]))
            touch_env_dir(venv_dir)
            out["env_dirs"].append(venv_dir)
        for key, plugin in _plugins.items():
            if key in runtime_env:
                plugin.build(runtime_env[key], controller_client, out)
    except ValueError as e:
        # Spec validation problems are deterministic on every node.
        raise RuntimeEnvBuildError(str(e)) from e
    # Everything else: RuntimeEnvBuildError only where the RAISE SITE
    # knows the failure is deterministic (bad pip requirement, package
    # missing from the KV, missing image root). Node-local trouble (full
    # disk, transport blips) stays generic so the lease loop can exclude
    # the node and re-pick instead of aborting the submission.
    return out


def normalize(runtime_env: Dict[str, Any]) -> Dict[str, Any]:
    """Validate + normalize a runtime_env spec (uploads local working_dir
    automatically when the cluster spans hosts is the caller's choice —
    pass a kv:// URI for that)."""
    out: Dict[str, Any] = {}
    env_vars = runtime_env.get("env_vars")
    if env_vars:
        out["env_vars"] = {str(k): str(v) for k, v in env_vars.items()}
    wd = runtime_env.get("working_dir")
    if wd:
        out["working_dir"] = str(wd)
    mods = runtime_env.get("py_modules")
    if mods:
        if not isinstance(mods, (list, tuple)):
            raise ValueError("runtime_env['py_modules'] must be a list of "
                             "paths or kv:// URIs")
        out["py_modules"] = [str(m) for m in mods]
    pip = runtime_env.get("pip")
    if pip:
        if not isinstance(pip, (list, tuple)) or not all(
                isinstance(p, str) for p in pip):
            raise ValueError("runtime_env['pip'] must be a list of "
                             "requirement strings")
        out["pip"] = list(pip)
    for key, plugin in _plugins.items():
        if key in runtime_env:
            out[key] = plugin.validate(runtime_env[key])
    known = {"env_vars", "working_dir", "py_modules", "pip"} | set(_plugins)
    unknown = set(runtime_env) - known
    if unknown:
        raise ValueError(f"unsupported runtime_env keys: {sorted(unknown)} "
                         f"(supported: {sorted(known)})")
    return out
