"""Runtime environments: per-task/actor isolated Python environments.

Analogue of the reference's runtime-env subsystem
(``_private/runtime_env/agent/runtime_env_agent.py:162`` builds envs on
each node; ``packaging.py`` ships working_dir/py_modules zips through the
GCS KV; ``runtime_env/pip.py`` builds per-env virtualenvs). The supported
spec keys:

* ``env_vars``: dict merged into the worker's environment at fork.
* ``working_dir``: local path (same-host clusters) or ``kv://<key>`` from
  :func:`upload_working_dir` — extracted once per node per env hash, set
  as the worker's cwd and prepended to ``PYTHONPATH``.
* ``py_modules``: list of module/package paths or ``kv://`` zips from
  :func:`upload_py_module` — each lands on the worker's ``PYTHONPATH``.
* ``pip``: list of requirement strings (or local wheel paths). Built into
  a per-hash virtualenv on each node (``--system-site-packages`` so jax &
  friends stay visible — the TPU stack must not be reinstalled per env),
  cached across leases; the worker forks from the venv's interpreter.
  Build failures surface at lease time as the task's error (reference:
  ``pip.py`` + the agent's CreateRuntimeEnv reply).

Workers are pooled per runtime-env hash (reference: worker_pool.h's
runtime_env_hash matching), so repeated tasks with the same env reuse
their workers.
"""

from __future__ import annotations

import io
import os
import subprocess
import sys
import threading
import zipfile
from typing import Any, Dict, List, Optional

ENV_ROOT = "/tmp/ray_tpu_envs"

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
_MAX_PACKAGE_BYTES = 100 * 1024 * 1024


def package_working_dir(path: str, arcname_prefix: str = "") -> bytes:
    """Zip a directory (reference: packaging.py's package zips), size-capped.
    ``arcname_prefix`` nests the content under one directory inside the
    archive (py_modules packages zip under their own name)."""
    buf = io.BytesIO()
    root = os.path.abspath(path)
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_DIRS]
            for fname in filenames:
                full = os.path.join(dirpath, fname)
                total += os.path.getsize(full)
                if total > _MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"package {path} exceeds "
                        f"{_MAX_PACKAGE_BYTES >> 20} MiB")
                zf.write(full, os.path.join(
                    arcname_prefix, os.path.relpath(full, root)))
    return buf.getvalue()


def _upload_blob(blob: bytes) -> str:
    import hashlib

    from ray_tpu.core.runtime import get_core_worker

    key = f"__pkg__/{hashlib.sha1(blob).hexdigest()[:20]}.zip"
    get_core_worker().controller.call("kv_put", key, blob)
    return f"kv://{key}"


def upload_working_dir(path: str) -> str:
    """Package + upload a working dir to the cluster KV; returns the
    ``kv://`` URI to put in ``runtime_env['working_dir']``."""
    return _upload_blob(package_working_dir(path))


def materialize_working_dir(spec: str, controller_client) -> str:
    """Resolve a working_dir spec to a local directory: plain paths pass
    through; ``kv://`` packages are fetched from the controller KV and
    extracted once per content hash (used by the worker pool AND job
    supervisors)."""
    if not str(spec).startswith("kv://"):
        return str(spec)
    import hashlib

    key = str(spec)[len("kv://"):]
    dest = os.path.join(ENV_ROOT,
                        hashlib.sha1(key.encode()).hexdigest()[:16])
    marker = os.path.join(dest, ".ready")
    if not os.path.exists(marker):
        blob = controller_client.call("kv_get", key)
        if blob is None:
            raise RuntimeError(f"working_dir package {key} not in KV")
        os.makedirs(dest, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            zf.extractall(dest)
        with open(marker, "w") as f:
            f.write("ok")
    return dest


def upload_py_module(path: str) -> str:
    """Package one module/package (zipped UNDER its own name, so the
    extraction dir is a valid sys.path entry) and upload to the KV; returns
    the ``kv://`` URI for ``runtime_env['py_modules']`` (reference:
    packaging.py py_modules upload)."""
    root = os.path.abspath(path)
    name = os.path.basename(root.rstrip("/"))
    if os.path.isfile(root):
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.write(root, name)
        return _upload_blob(buf.getvalue())
    return _upload_blob(package_working_dir(root, arcname_prefix=name))


def materialize_py_module(spec: str, controller_client) -> str:
    """Resolve one py_modules entry to a sys.path directory: ``kv://``
    zips extract (cached per content) and the extraction dir is the path
    entry; plain paths contribute their parent directory."""
    if str(spec).startswith("kv://"):
        return materialize_working_dir(spec, controller_client)
    return os.path.dirname(os.path.abspath(str(spec)))


# ----------------------------------------------------------- pip / venv

_pip_lock = threading.Lock()


def pip_env_dir(pip: List[str]) -> str:
    import hashlib
    import json

    key = hashlib.sha1(
        json.dumps(list(pip), sort_keys=True).encode()).hexdigest()[:16]
    return os.path.join(ENV_ROOT, f"venv-{key}")


def ensure_pip_env(pip: List[str]) -> str:
    """Build (once, cached per requirement-list hash) a virtualenv with the
    requested packages; returns its python executable. The venv sees the
    base interpreter's site-packages (--system-site-packages), so the
    heavyweight TPU stack is inherited, not reinstalled (reference:
    runtime_env/pip.py builds a venv per env and caches by URI hash)."""
    dest = pip_env_dir(pip)
    python = os.path.join(dest, "bin", "python")
    marker = os.path.join(dest, ".ready")
    if os.path.exists(marker):
        return python
    with _pip_lock:  # serialize builds in this node process
        if os.path.exists(marker):
            return python
        build = f"{dest}.build-{os.getpid()}"
        try:
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 build],
                check=True, capture_output=True, text=True, timeout=300)
            proc = subprocess.run(
                [os.path.join(build, "bin", "python"), "-m", "pip",
                 "install", "--no-input", *pip],
                capture_output=True, text=True, timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pip install {pip} failed: "
                    f"{(proc.stderr or proc.stdout)[-800:]}")
            with open(os.path.join(build, ".ready"), "w") as f:
                f.write("ok")
            try:
                os.rename(build, dest)
            except OSError:
                if not os.path.exists(marker):  # lost a cross-process race
                    raise
        finally:
            import shutil

            shutil.rmtree(build, ignore_errors=True)
    return python


def build_env(runtime_env: Dict[str, Any],
              controller_client) -> Dict[str, Any]:
    """Materialize a full runtime env on this node. Returns
    ``{python, pythonpath, cwd, env_vars}`` for the worker fork; raises on
    build failure (the node surfaces it in the lease reply — reference:
    the raylet failing a lease when the agent's CreateRuntimeEnv errors)."""
    out: Dict[str, Any] = {
        "python": None,
        "pythonpath": [],
        "cwd": None,
        "env_vars": {str(k): str(v) for k, v in
                     (runtime_env.get("env_vars") or {}).items()},
    }
    wd = runtime_env.get("working_dir")
    if wd:
        out["cwd"] = materialize_working_dir(wd, controller_client)
        out["pythonpath"].append(out["cwd"])
    for mod in runtime_env.get("py_modules") or []:
        out["pythonpath"].append(
            materialize_py_module(mod, controller_client))
    pip = runtime_env.get("pip")
    if pip:
        out["python"] = ensure_pip_env(list(pip))
    return out


def normalize(runtime_env: Dict[str, Any]) -> Dict[str, Any]:
    """Validate + normalize a runtime_env spec (uploads local working_dir
    automatically when the cluster spans hosts is the caller's choice —
    pass a kv:// URI for that)."""
    out: Dict[str, Any] = {}
    env_vars = runtime_env.get("env_vars")
    if env_vars:
        out["env_vars"] = {str(k): str(v) for k, v in env_vars.items()}
    wd = runtime_env.get("working_dir")
    if wd:
        out["working_dir"] = str(wd)
    mods = runtime_env.get("py_modules")
    if mods:
        if not isinstance(mods, (list, tuple)):
            raise ValueError("runtime_env['py_modules'] must be a list of "
                             "paths or kv:// URIs")
        out["py_modules"] = [str(m) for m in mods]
    pip = runtime_env.get("pip")
    if pip:
        if not isinstance(pip, (list, tuple)) or not all(
                isinstance(p, str) for p in pip):
            raise ValueError("runtime_env['pip'] must be a list of "
                             "requirement strings")
        out["pip"] = list(pip)
    unknown = set(runtime_env) - {"env_vars", "working_dir", "py_modules",
                                  "pip"}
    if unknown:
        raise ValueError(f"unsupported runtime_env keys: {sorted(unknown)} "
                         "(supported: env_vars, working_dir, py_modules, "
                         "pip)")
    return out
