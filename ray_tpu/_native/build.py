"""Build the native shared library (g++ -shared), cached by source mtime.

The reference builds its native layer with bazel (``BUILD.bazel``); here the
native surface is small enough that a direct g++ invocation at first import
keeps the dev loop to sub-second rebuilds. The built ``.so`` lands next to the
sources in ``build/``.
"""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_DIR, "build")
_LOCK = threading.Lock()


def build_library(name: str, sources: list, extra_flags: list = ()) -> str:
    """Compile ``sources`` (relative to _native/) into build/lib<name>.so,
    rebuilding only when a source is newer than the output. Returns the path.
    """
    out = os.path.join(_BUILD_DIR, f"lib{name}.so")
    srcs = [os.path.join(_DIR, s) for s in sources]
    with _LOCK:
        if os.path.exists(out):
            out_mtime = os.path.getmtime(out)
            if all(os.path.getmtime(s) <= out_mtime for s in srcs):
                return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = out + f".tmp.{os.getpid()}"
        cmd = ["g++", "-O2", "-g", "-shared", "-fPIC", "-std=c++17",
               "-pthread", *extra_flags, "-o", tmp, *srcs]
        # Compiling under _LOCK is deliberate: one build per process,
        # everyone else waits for the .so instead of racing g++.
        # graftlint: disable=lock-held-blocking
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)  # atomic: concurrent builders race safely
    return out
