// Shared-memory object store: the plasma equivalent, TPU-era.
//
// Analogue of the reference's plasma store
// (src/ray/object_manager/plasma/: store.h:55, object_store.h:74,
// object_lifecycle_manager.h:101, eviction_policy.h) redesigned for the TPU
// host: instead of a store *server* process with fd-passing (fling.cc) and a
// socket protocol (plasma.fbs), the store is a single mmap'd file in /dev/shm
// shared by every process on the node, with all metadata — object table,
// free-list allocator, LRU clock — living inside the mapping, guarded by one
// process-shared robust mutex. Rationale: on a TPU VM every reader stages
// into the same host RAM that feeds TPU infeed; a serverless design removes
// one IPC round-trip and one copy from the get path (readers mmap once and
// take zero-copy views), and crash-robustness comes from the robust mutex +
// pin reclamation rather than a supervising server.
//
// Layout:
//   [Header | Slot table (n_slots) | data region]
// Data region is managed by a first-fit free list with coalescing
// (the reference uses dlmalloc inside its mmap'd slabs).
//
// Concurrency: one robust PTHREAD_PROCESS_SHARED mutex in the header. All
// operations are short (no IO under lock). If a process dies holding the
// lock, the next locker gets EOWNERDEAD and recovers the state.
//
// Object lifecycle: CREATED (being written) -> SEALED (immutable, readable)
// -> freed. Readers pin objects (refcount) to keep eviction away; eviction
// is LRU over sealed, unpinned objects and only runs on allocation pressure
// (reference: eviction_policy.h LRU cache + create-request queue).

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055534852ULL;  // "RTPUSHR"
constexpr uint64_t kAlign = 64;                   // TPU-friendly host staging
constexpr uint32_t kIdSize = 16;

enum SlotState : uint32_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,  // deleted; probe chains continue through it
};

struct Slot {
  uint8_t id[kIdSize];
  uint64_t offset;      // into data region
  uint64_t size;        // object payload size (may be 0)
  uint64_t alloc_size;  // bytes actually taken from the free list
  uint32_t state;
  uint32_t pins;
  uint64_t lru_tick;
  uint64_t owner_pid;   // creator pid: lets eviction reclaim CREATED slots
                        // whose writer died between create and seal
};

// Free-list block header, stored inside the data region.
struct FreeBlock {
  uint64_t size;      // includes this header? no: payload bytes following
  uint64_t next_off;  // offset of next free block, or ~0ULL
};

constexpr uint64_t kNilOff = ~0ULL;

struct Header {
  uint64_t magic;
  uint64_t total_size;     // whole file
  uint64_t n_slots;
  uint64_t data_off;       // start of data region
  uint64_t data_size;
  uint64_t free_head;      // offset (data-relative) of first free block
  uint64_t used_bytes;
  uint64_t lru_clock;
  uint64_t num_objects;
  pthread_mutex_t mutex;
};

struct Handle {
  int fd;
  uint8_t* base;
  uint64_t mapped_size;
};

inline Header* header(Handle* h) { return reinterpret_cast<Header*>(h->base); }

inline Slot* slots(Handle* h) {
  return reinterpret_cast<Slot*>(h->base + sizeof(Header));
}

inline uint8_t* data(Handle* h) { return h->base + header(h)->data_off; }

inline uint64_t align_up(uint64_t v) {
  return (v + kAlign - 1) & ~(kAlign - 1);
}

// FNV-1a over the id for slot hashing.
inline uint64_t hash_id(const uint8_t* id) {
  uint64_t acc = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdSize; ++i) {
    acc ^= id[i];
    acc *= 1099511628211ULL;
  }
  return acc;
}

class Locker {
 public:
  explicit Locker(Handle* h) : h_(h) {
    int rc = pthread_mutex_lock(&header(h_)->mutex);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock; state is consistent because all
      // mutations are applied atomically enough for our purposes (worst
      // case: a leaked CREATED object, cleaned up by eviction).
      pthread_mutex_consistent(&header(h_)->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&header(h_)->mutex); }

 private:
  Handle* h_;
};

Slot* find_slot(Handle* h, const uint8_t* id) {
  Header* hd = header(h);
  Slot* table = slots(h);
  uint64_t mask = hd->n_slots - 1;
  uint64_t idx = hash_id(id) & mask;
  for (uint64_t probe = 0; probe < hd->n_slots; ++probe) {
    Slot* s = &table[(idx + probe) & mask];
    if (s->state == kEmpty) return nullptr;
    if (s->state != kTombstone && memcmp(s->id, id, kIdSize) == 0) return s;
  }
  return nullptr;
}

Slot* find_insert_slot(Handle* h, const uint8_t* id) {
  Header* hd = header(h);
  Slot* table = slots(h);
  uint64_t mask = hd->n_slots - 1;
  uint64_t idx = hash_id(id) & mask;
  Slot* first_tomb = nullptr;
  for (uint64_t probe = 0; probe < hd->n_slots; ++probe) {
    Slot* s = &table[(idx + probe) & mask];
    if (s->state == kEmpty) return first_tomb ? first_tomb : s;
    if (s->state == kTombstone) {
      if (!first_tomb) first_tomb = s;
    } else if (memcmp(s->id, id, kIdSize) == 0) {
      return nullptr;  // already exists
    }
  }
  return first_tomb;  // table full unless a tombstone is reusable
}

// Allocate from the first-fit free list. Returns data-relative offset or
// kNilOff; *actual receives the true block size taken (>= requested after
// alignment; may absorb an unsplittable sliver), which the caller must
// record for the matching freelist_free.
uint64_t freelist_alloc(Handle* h, uint64_t size, uint64_t* actual) {
  Header* hd = header(h);
  size = align_up(size);
  uint64_t prev = kNilOff;
  uint64_t cur = hd->free_head;
  while (cur != kNilOff) {
    FreeBlock* blk = reinterpret_cast<FreeBlock*>(data(h) + cur);
    if (blk->size >= size) {
      uint64_t remaining = blk->size - size;
      uint64_t next;
      if (remaining >= sizeof(FreeBlock) + kAlign) {
        uint64_t rest_off = cur + size;
        FreeBlock* rest = reinterpret_cast<FreeBlock*>(data(h) + rest_off);
        rest->size = remaining;
        rest->next_off = blk->next_off;
        next = rest_off;
      } else {
        size = blk->size;  // absorb the sliver
        next = blk->next_off;
      }
      if (prev == kNilOff) {
        hd->free_head = next;
      } else {
        reinterpret_cast<FreeBlock*>(data(h) + prev)->next_off = next;
      }
      hd->used_bytes += size;
      *actual = size;
      return cur;
    }
    prev = cur;
    cur = blk->next_off;
  }
  return kNilOff;
}

// Return a block to the free list, keeping it sorted by offset and
// coalescing neighbors.
void freelist_free(Handle* h, uint64_t off, uint64_t size) {
  // `size` is the alloc_size recorded at allocation time (already aligned,
  // sliver included), so used_bytes accounting is exact.
  Header* hd = header(h);
  hd->used_bytes -= size;
  uint64_t prev = kNilOff;
  uint64_t cur = hd->free_head;
  while (cur != kNilOff && cur < off) {
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(data(h) + cur)->next_off;
  }
  FreeBlock* blk = reinterpret_cast<FreeBlock*>(data(h) + off);
  blk->size = size;
  blk->next_off = cur;
  if (prev == kNilOff) {
    hd->free_head = off;
  } else {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(data(h) + prev);
    if (prev + pb->size == off) {  // coalesce with prev
      pb->size += size;
      pb->next_off = cur;
      blk = pb;
      off = prev;
    } else {
      pb->next_off = off;
    }
  }
  if (cur != kNilOff && off + blk->size == cur) {  // coalesce with next
    FreeBlock* nb = reinterpret_cast<FreeBlock*>(data(h) + cur);
    blk->size += nb->size;
    blk->next_off = nb->next_off;
  }
}

void release_slot(Handle* h, Slot* s) {
  freelist_free(h, s->offset, s->alloc_size);
  s->state = kTombstone;
  s->pins = 0;
  header(h)->num_objects--;
}

inline bool pid_dead(uint64_t pid) {
  return pid != 0 && kill((pid_t)pid, 0) != 0 && errno == ESRCH;
}

// Evict sealed, unpinned objects (lowest lru_tick first) until at least
// `needed` aligned bytes could plausibly be free. Returns evicted count.
// Also reclaims CREATED slots whose creator process died between
// shm_create and shm_seal (the EOWNERDEAD-leak case).
int evict_for(Handle* h, uint64_t needed) {
  Header* hd = header(h);
  Slot* table = slots(h);
  int evicted = 0;
  for (uint64_t i = 0; i < hd->n_slots; ++i) {
    Slot* s = &table[i];
    if (s->state == kCreated && pid_dead(s->owner_pid)) {
      release_slot(h, s);
      ++evicted;
    }
  }
  while (hd->used_bytes + align_up(needed) > hd->data_size) {
    Slot* victim = nullptr;
    for (uint64_t i = 0; i < hd->n_slots; ++i) {
      Slot* s = &table[i];
      if (s->state == kSealed && s->pins == 0 &&
          (!victim || s->lru_tick < victim->lru_tick)) {
        victim = s;
      }
    }
    if (!victim) break;
    release_slot(h, victim);
    ++evicted;
  }
  return evicted;
}

}  // namespace

extern "C" {

// Create (or recreate) a store file of `capacity` data bytes. Returns 0 on
// success.
int shm_store_create(const char* path, uint64_t capacity, uint64_t n_slots) {
  if (n_slots == 0) n_slots = 1 << 16;
  // round n_slots to power of two
  uint64_t p2 = 1;
  while (p2 < n_slots) p2 <<= 1;
  n_slots = p2;

  uint64_t data_off = align_up(sizeof(Header) + n_slots * sizeof(Slot));
  uint64_t total = data_off + align_up(capacity);
  int fd = open(path, O_RDWR | O_CREAT | O_TRUNC, 0600);
  if (fd < 0) return -errno;
  if (ftruncate(fd, (off_t)total) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  void* base =
      mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    int e = errno;
    close(fd);
    return -e;
  }
  Header* hd = reinterpret_cast<Header*>(base);
  memset(hd, 0, sizeof(Header));
  hd->total_size = total;
  hd->n_slots = n_slots;
  hd->data_off = data_off;
  hd->data_size = align_up(capacity);
  hd->used_bytes = 0;
  hd->lru_clock = 1;
  hd->num_objects = 0;
  memset(static_cast<uint8_t*>(base) + sizeof(Header), 0,
         n_slots * sizeof(Slot));
  // Whole data region is one free block.
  FreeBlock* first = reinterpret_cast<FreeBlock*>(
      static_cast<uint8_t*>(base) + data_off);
  first->size = hd->data_size;
  first->next_off = kNilOff;
  hd->free_head = 0;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hd->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  hd->magic = kMagic;  // last: marks the store valid
  munmap(base, total);
  close(fd);
  return 0;
}

void* shm_store_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                    fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* hd = reinterpret_cast<Header*>(base);
  if (hd->magic != kMagic || hd->total_size != (uint64_t)st.st_size) {
    munmap(base, st.st_size);
    close(fd);
    return nullptr;
  }
  Handle* h = new Handle{fd, static_cast<uint8_t*>(base),
                         (uint64_t)st.st_size};
  return h;
}

void shm_store_close(void* vh) {
  Handle* h = static_cast<Handle*>(vh);
  if (!h) return;
  munmap(h->base, h->mapped_size);
  close(h->fd);
  delete h;
}

uint8_t* shm_store_base(void* vh) { return static_cast<Handle*>(vh)->base; }

// Allocate an object buffer. Returns absolute offset from the mapping base
// (>0) or 0 on failure (full table / OOM after eviction / duplicate id).
uint64_t shm_create(void* vh, const uint8_t* id, uint64_t size) {
  Handle* h = static_cast<Handle*>(vh);
  Locker lock(h);
  Header* hd = header(h);
  if (align_up(size) > hd->data_size) return 0;
  Slot* s = find_insert_slot(h, id);
  if (!s) return 0;
  uint64_t want = size ? size : kAlign;  // 0-byte objects take one unit
  uint64_t actual = 0;
  uint64_t off = freelist_alloc(h, want, &actual);
  if (off == kNilOff) {
    evict_for(h, want);
    off = freelist_alloc(h, want, &actual);
    if (off == kNilOff) return 0;
  }
  memcpy(s->id, id, kIdSize);
  s->offset = off;
  s->size = size;  // true payload size (0 allowed)
  s->alloc_size = actual;
  s->state = kCreated;
  s->pins = 1;  // creator holds a pin until seal
  s->lru_tick = hd->lru_clock++;
  s->owner_pid = (uint64_t)getpid();
  hd->num_objects++;
  return hd->data_off + off;
}

// Seal: object becomes immutable + readable. keep_pin != 0 converts the
// creator pin into a primary-copy pin (owner releases it via shm_unpin when
// the object goes out of scope), so eviction can never drop the only copy
// of a live object (reference pins primary copies the same way,
// local_object_manager.h).
int shm_seal2(void* vh, const uint8_t* id, int keep_pin) {
  Handle* h = static_cast<Handle*>(vh);
  Locker lock(h);
  Slot* s = find_slot(h, id);
  if (!s || s->state != kCreated) return -1;
  s->state = kSealed;
  if (!keep_pin && s->pins > 0) s->pins--;
  return 0;
}

int shm_seal(void* vh, const uint8_t* id) { return shm_seal2(vh, id, 0); }

// Look up a sealed object. On success returns absolute offset, fills *size,
// and pins the object if pin != 0. Returns 0 if absent/unsealed.
uint64_t shm_get(void* vh, const uint8_t* id, uint64_t* size, int pin) {
  Handle* h = static_cast<Handle*>(vh);
  Locker lock(h);
  Header* hd = header(h);
  Slot* s = find_slot(h, id);
  if (!s || s->state != kSealed) return 0;
  if (size) *size = s->size;
  if (pin) s->pins++;
  s->lru_tick = hd->lru_clock++;
  return hd->data_off + s->offset;
}

int shm_unpin(void* vh, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(vh);
  Locker lock(h);
  Slot* s = find_slot(h, id);
  if (!s || s->pins == 0) return -1;
  s->pins--;
  return 0;
}

int shm_contains(void* vh, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(vh);
  Locker lock(h);
  Slot* s = find_slot(h, id);
  return (s && s->state == kSealed) ? 1 : 0;
}

// Delete an object (any state) regardless of pins — callers coordinate.
int shm_delete(void* vh, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(vh);
  Locker lock(h);
  Slot* s = find_slot(h, id);
  if (!s) return -1;
  release_slot(h, s);
  return 0;
}

uint64_t shm_used_bytes(void* vh) {
  Handle* h = static_cast<Handle*>(vh);
  Locker lock(h);
  return header(h)->used_bytes;
}

uint64_t shm_capacity(void* vh) {
  Handle* h = static_cast<Handle*>(vh);
  return header(h)->data_size;
}

uint64_t shm_num_objects(void* vh) {
  Handle* h = static_cast<Handle*>(vh);
  Locker lock(h);
  return header(h)->num_objects;
}

}  // extern "C"
