"""ctypes bindings for the C++ shared-memory object store.

Client-side analogue of the reference's ``plasma/client.cc``: create/seal for
writers, zero-copy pinned views for readers. A view pins its object in the
store until released (the reference pins via client-connection bookkeeping;
here the pin is an explicit refcount dropped by ``ShmView.release`` or GC).
"""

from __future__ import annotations

import ctypes
import mmap
import os
from typing import Optional

from ray_tpu._native.build import build_library

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = build_library("shm_store", ["shm_store.cpp"])
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        # A prebuilt .so that doesn't load on THIS host (e.g. linked
        # against a newer glibc) is stale regardless of mtime: rebuild
        # from source with the local toolchain and retry.
        try:
            os.remove(path)
        except OSError:
            pass
        path = build_library("shm_store", ["shm_store.cpp"])
        lib = ctypes.CDLL(path)
    lib.shm_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                     ctypes.c_uint64]
    lib.shm_store_create.restype = ctypes.c_int
    lib.shm_store_open.argtypes = [ctypes.c_char_p]
    lib.shm_store_open.restype = ctypes.c_void_p
    lib.shm_store_close.argtypes = [ctypes.c_void_p]
    lib.shm_store_base.argtypes = [ctypes.c_void_p]
    lib.shm_store_base.restype = ctypes.c_void_p
    lib.shm_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint64]
    lib.shm_create.restype = ctypes.c_uint64
    lib.shm_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_seal.restype = ctypes.c_int
    lib.shm_seal2.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_int]
    lib.shm_seal2.restype = ctypes.c_int
    lib.shm_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
    lib.shm_get.restype = ctypes.c_uint64
    lib.shm_unpin.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_unpin.restype = ctypes.c_int
    lib.shm_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_contains.restype = ctypes.c_int
    lib.shm_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_delete.restype = ctypes.c_int
    lib.shm_used_bytes.argtypes = [ctypes.c_void_p]
    lib.shm_used_bytes.restype = ctypes.c_uint64
    lib.shm_capacity.argtypes = [ctypes.c_void_p]
    lib.shm_capacity.restype = ctypes.c_uint64
    lib.shm_num_objects.argtypes = [ctypes.c_void_p]
    lib.shm_num_objects.restype = ctypes.c_uint64
    _lib = lib
    return lib


class ShmView:
    """A pinned, zero-copy readable view of a sealed object."""

    def __init__(self, store: "ShmStore", object_id: bytes, mv: memoryview):
        self._store = store
        self._object_id = object_id
        self.data = mv
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self.data = None
            self._store._unpin(self._object_id)

    def __del__(self):
        try:
            self.release()
        except Exception:  # graftlint: disable=swallowed-exception (interpreter-teardown __del__)
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class ShmPin:
    """A primary-copy pin taken at put time (no data view). Released by the
    owner when the object leaves scope; keeps LRU eviction away from the
    only copy of a live object."""

    def __init__(self, store: "ShmStore", object_id: bytes):
        self._store = store
        self._object_id = object_id
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._store._unpin(self._object_id)

    def __del__(self):
        try:
            self.release()
        except Exception:  # graftlint: disable=swallowed-exception (interpreter-teardown __del__)
            pass


class ShmStore:
    """One per process per store file; all methods thread-safe (locking lives
    in the C++ layer)."""

    def __init__(self, path: str):
        self._lib = _load()
        self.path = path
        self._handle = self._lib.shm_store_open(path.encode())
        if not self._handle:
            raise OSError(f"cannot open shm store at {path}")
        # Re-map read-write through Python mmap for zero-copy memoryviews
        # (the C++ mapping isn't exposed as a buffer).
        self._fd = os.open(path, os.O_RDWR)
        size = os.fstat(self._fd).st_size
        self._map = mmap.mmap(self._fd, size)
        self._mv = memoryview(self._map)

    @staticmethod
    def create(path: str, capacity: int, n_slots: int = 0) -> "ShmStore":
        lib = _load()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        rc = lib.shm_store_create(path.encode(), capacity, n_slots)
        if rc != 0:
            raise OSError(f"shm_store_create({path}) failed: {rc}")
        return ShmStore(path)

    # ------------------------------------------------------------ writer

    def put_bytes(self, object_id: bytes, payload, pin: bool = False):
        """Create + copy + seal. Returns None when the store can't fit it;
        otherwise True, or a ShmPin when ``pin`` (the primary-copy pin the
        owner must hold until the object is freed)."""
        n = len(payload)
        off = self._lib.shm_create(self._handle, object_id, n)
        if off == 0:
            return None
        self._mv[off:off + n] = payload
        self._lib.shm_seal2(self._handle, object_id, 1 if pin else 0)
        return ShmPin(self, object_id) if pin else True

    def create_buffer(self, object_id: bytes, size: int):
        """Reserve a writable buffer; caller fills it then calls seal()."""
        off = self._lib.shm_create(self._handle, object_id, size)
        if off == 0:
            return None
        return self._mv[off:off + size]

    def seal(self, object_id: bytes, pin: bool = False):
        """Seal a buffer created via create_buffer; with ``pin`` the primary
        copy stays unevictable and the returned ShmPin must be held."""
        if pin:
            self._lib.shm_seal2(self._handle, object_id, 1)
            return ShmPin(self, object_id)
        self._lib.shm_seal(self._handle, object_id)
        return None

    # ------------------------------------------------------------ reader

    def get_view(self, object_id: bytes) -> Optional[ShmView]:
        size = ctypes.c_uint64()
        off = self._lib.shm_get(self._handle, object_id,
                                ctypes.byref(size), 1)
        if off == 0:
            return None
        return ShmView(self, object_id, self._mv[off:off + size.value])

    def contains(self, object_id: bytes) -> bool:
        return bool(self._lib.shm_contains(self._handle, object_id))

    def _unpin(self, object_id: bytes) -> None:
        self._lib.shm_unpin(self._handle, object_id)

    def delete(self, object_id: bytes) -> bool:
        return self._lib.shm_delete(self._handle, object_id) == 0

    # ------------------------------------------------------------- stats

    def used_bytes(self) -> int:
        return self._lib.shm_used_bytes(self._handle)

    def capacity(self) -> int:
        return self._lib.shm_capacity(self._handle)

    def num_objects(self) -> int:
        return self._lib.shm_num_objects(self._handle)

    def close(self) -> None:
        if self._handle:
            self._mv.release()
            self._map.close()
            os.close(self._fd)
            self._lib.shm_store_close(self._handle)
            self._handle = None
