"""Block format + interchange: numpy-columnar blocks with Arrow interop.

The reference's Ray Data blocks are Arrow tables
(``data/_internal/arrow_block.py``); here the canonical in-store block is a
**dict of numpy column arrays** — the TPU-first choice, because every block's
terminal consumer is ``jax.device_put`` / infeed, which wants contiguous
numpy, and the shm store already ships numpy zero-copy via pickle5 buffers.
Arrow remains the *interchange* format: blocks convert to/from
``pyarrow.Table`` (zero-copy for primitive columns in both directions —
Arrow buffers wrap the numpy memory and ``to_numpy(zero_copy_only=...)``
wraps back) for schema typing, parquet IO, and ``map_batches``
``batch_format="pyarrow"|"pandas"``.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

Block = Dict[str, np.ndarray]


def block_len(block: Block) -> int:
    if not block:
        return 0
    return len(next(iter(block.values())))


def block_nbytes(block: Block) -> int:
    return int(sum(getattr(v, "nbytes", 0) for v in block.values()))


def concat_blocks(blocks: List[Block]) -> Block:
    keys = blocks[0].keys()
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def slice_block(block: Block, start: int, end: int) -> Block:
    return {k: v[start:end] for k, v in block.items()}


# ------------------------------------------------------------------ schema


class Schema:
    """Column name -> Arrow type (+ numpy dtype and element shape), derived
    without copying data (reference: ``Dataset.schema()`` returning the
    Arrow schema)."""

    def __init__(self, block: Block):
        import pyarrow as pa

        self.names: List[str] = list(block.keys())
        self.types: Dict[str, Any] = {}
        self.dtypes: Dict[str, np.dtype] = {}
        self.shapes: Dict[str, Tuple[int, ...]] = {}
        for name, col in block.items():
            self.dtypes[name] = col.dtype
            self.shapes[name] = tuple(col.shape[1:])
            if col.ndim == 1:
                try:
                    self.types[name] = pa.from_numpy_dtype(col.dtype)
                except (pa.ArrowNotImplementedError, TypeError):
                    self.types[name] = pa.binary()
            else:  # tensor column
                self.types[name] = pa.list_(
                    pa.from_numpy_dtype(col.dtype)
                    if col.dtype.kind not in "OUS" else pa.string())

    def __repr__(self) -> str:
        cols = ", ".join(
            f"{n}: {self.types[n]}"
            + (f"{list(self.shapes[n])}" if self.shapes[n] else "")
            for n in self.names)
        return f"Schema({cols})"

    def __iter__(self) -> Iterator[str]:
        return iter(self.names)

    def __getitem__(self, name: str) -> Tuple[np.dtype, Tuple[int, ...]]:
        """Back-compat mapping view: name -> (numpy dtype, element shape)."""
        return self.dtypes[name], self.shapes[name]

    def __contains__(self, name: str) -> bool:
        return name in self.dtypes

    def __len__(self) -> int:
        return len(self.names)


# ------------------------------------------------------- format conversion


def to_arrow(block: Block):
    """Block -> pyarrow.Table. 1-D primitive columns wrap the numpy memory
    (zero-copy); tensor columns (ndim > 1) flatten into fixed-size-list
    arrays over the same buffer."""
    import pyarrow as pa

    import json

    def wrap_1d(col: np.ndarray):
        """Numeric contiguous arrays wrap their buffer (no copy); strings
        and objects go through pa.array (copy — Arrow's layout differs)."""
        if col.dtype.kind in "iuf" and col.flags.c_contiguous:
            typ = pa.from_numpy_dtype(col.dtype)
            return pa.Array.from_buffers(
                typ, len(col), [None, pa.py_buffer(col)])
        return pa.array(col)

    arrays, fields = [], []
    for name, col in block.items():
        if col.ndim == 1:
            arr = wrap_1d(col)
            fields.append(pa.field(name, arr.type))
        else:
            inner = int(np.prod(col.shape[1:]))
            flat = wrap_1d(np.ascontiguousarray(col).reshape(-1))
            arr = pa.FixedSizeListArray.from_arrays(flat, inner)
            # Arrow's FixedSizeList is rank-1: the true element shape rides
            # in field metadata so >2-D tensors round-trip unflattened.
            fields.append(pa.field(
                name, arr.type,
                metadata={b"tensor_shape":
                          json.dumps(list(col.shape[1:])).encode()}))
        arrays.append(arr)
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def from_arrow(table) -> Block:
    """pyarrow.Table -> Block. Primitive columns come back zero-copy when
    Arrow's layout allows (single chunk, no nulls); strings and nested
    lists copy."""
    import json

    import pyarrow as pa

    out: Block = {}
    for name in table.column_names:
        col = table.column(name)
        field = table.schema.field(name)
        if isinstance(col, pa.ChunkedArray):
            # Single-chunk columns stay zero-copy (combine_chunks would
            # reallocate even for one chunk).
            col = (col.chunk(0) if col.num_chunks == 1
                   else col.combine_chunks())
        if pa.types.is_fixed_size_list(col.type):
            inner = col.type.list_size
            # flatten() honors the slice offset; .values would return the
            # unsliced child buffer.
            values = col.flatten().to_numpy(zero_copy_only=False)
            shape: Any = (inner,)
            meta = field.metadata or {}
            if b"tensor_shape" in meta:
                shape = tuple(json.loads(meta[b"tensor_shape"]))
            out[name] = values.reshape((len(col),) + tuple(shape))
        else:
            try:
                out[name] = col.to_numpy(zero_copy_only=True)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                out[name] = col.to_numpy(zero_copy_only=False)
    return out


def to_pandas(block: Block):
    import pandas as pd

    return pd.DataFrame({
        k: (list(v) if v.ndim > 1 else v) for k, v in block.items()})


def from_pandas(df) -> Block:
    out: Block = {}
    for name in df.columns:
        col = df[name].to_numpy()
        if len(col) and isinstance(col[0], np.ndarray):
            col = np.stack(col)
        out[name] = col
    return out


BATCH_FORMATS = ("numpy", "pyarrow", "pandas")


def wrap_batch_fn(fn, batch_format: str):
    """Adapt a user batch fn operating in ``batch_format`` to the canonical
    numpy block (reference: ``map_batches(batch_format=...)``,
    ``_internal/block_batching``). The fn may return any of the three
    formats regardless of its input format. Callers validate
    ``batch_format`` against :data:`BATCH_FORMATS` up front."""
    if batch_format == "numpy":
        convert_in = None
    elif batch_format == "pyarrow":
        convert_in = to_arrow
    else:
        convert_in = to_pandas

    def wrapped(block: Block) -> Block:
        out = fn(convert_in(block) if convert_in else block)
        return normalize_batch(out)

    return wrapped


def normalize_batch(out) -> Block:
    """Coerce a user-returned batch (numpy dict / Table / DataFrame) to the
    canonical block."""
    import pyarrow as pa

    if isinstance(out, dict):
        return {k: np.asarray(v) for k, v in out.items()}
    if isinstance(out, pa.Table):
        return from_arrow(out)
    try:
        import pandas as pd

        if isinstance(out, pd.DataFrame):
            return from_pandas(out)
    except ImportError:
        pass
    raise TypeError(
        f"map_batches fn must return a dict of arrays, pyarrow.Table or "
        f"pandas.DataFrame, got {type(out).__name__}")
