"""Device-side ingest prefetch: overlap host batch assembly + H2D transfer
with the jitted step.

VERDICT r4 Missing #5 (reference: ``Dataset.iter_batches``'s
``prefetch_batches`` pipelining, ``python/ray/data/dataset.py:3599``, and
Train ingest overlap, ``train/_internal/data_config.py:112``). The
TPU-native form: a background thread pulls the NEXT pad-to-static host
batch and issues ``jax.device_put`` — an async dispatch, so the PCIe/ICI
transfer runs while the current jitted step computes. The consumer simply
iterates device-resident (optionally mesh-sharded) batches; the step never
waits on fetch unless the pipeline genuinely underruns.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterator, Optional

_SENTINEL = object()


def device_prefetch(host_batches: Iterator[Any], mesh=None, rules=None,
                    prefetch: int = 2) -> Iterator[Any]:
    """Wrap a host-batch iterator into a device-batch iterator with
    ``prefetch`` batches in flight.

    With ``mesh``, batches land sharded batch-over-(data, fsdp) (the
    JaxTrainer ingest layout, via ``parallel.train_step.shard_batch``);
    without, they land on the default device. ``device_put`` inside the
    producer thread only DISPATCHES — the transfer itself is async and
    overlaps the consumer's running step."""
    import jax

    if mesh is not None:
        from ray_tpu.parallel.train_step import shard_batch

        def put(b):
            return shard_batch(b, mesh, rules)
    else:
        def put(b):
            return jax.tree.map(jax.device_put, b)

    q: "queue.Queue" = queue.Queue(maxsize=max(1, int(prefetch)))
    err: list = []

    def producer():
        try:
            for b in host_batches:
                q.put(put(b))
        except BaseException as e:  # surfaced on the consumer side
            err.append(e)
        finally:
            q.put(_SENTINEL)

    threading.Thread(target=producer, daemon=True,
                     name="device-prefetch").start()
    while True:
        item = q.get()
        if item is _SENTINEL:
            if err:
                raise err[0]
            return
        yield item
