"""Dataset creation APIs (reference: ``python/ray/data/read_api.py``)."""

from __future__ import annotations

import builtins
import glob as _glob
import math
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.dataset import Dataset


def _rows_to_block(rows: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    if not rows:
        return {}
    return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}


def from_items(items: List[Any], num_blocks: int = 8) -> Dataset:
    rows = [it if isinstance(it, dict) else {"item": it} for it in items]
    per = max(1, math.ceil(len(rows) / num_blocks))
    refs = [ray_tpu.put(_rows_to_block(rows[i:i + per]))
            for i in builtins.range(0, len(rows), per)]
    return Dataset(refs)


def range(n: int, num_blocks: int = 8) -> Dataset:
    per = max(1, math.ceil(n / num_blocks))
    refs = [ray_tpu.put({"id": np.arange(i, min(i + per, n))})
            for i in builtins.range(0, n, per)]
    return Dataset(refs)


def from_numpy(arrays: Dict[str, np.ndarray], num_blocks: int = 8) -> Dataset:
    if not arrays:
        return Dataset([])
    n = len(next(iter(arrays.values())))
    per = max(1, math.ceil(n / num_blocks))
    refs = [ray_tpu.put({k: v[i:i + per] for k, v in arrays.items()})
            for i in builtins.range(0, n, per)]
    return Dataset(refs)


def _read_files(paths, reader) -> Dataset:
    """One read task per file — parallel IO through the object store
    (reference: one read task per file fragment)."""
    if isinstance(paths, str):
        paths = sorted(_glob.glob(paths)) or [paths]
    read_task = ray_tpu.remote(reader)
    return Dataset([read_task.remote(p) for p in paths])


def read_parquet(paths) -> Dataset:
    def reader(path: str):
        import pyarrow.parquet as pq

        from ray_tpu.data.block import from_arrow

        # Tensor-aware: FixedSizeList columns with tensor_shape metadata
        # (written by write_parquet) come back as n-d numpy columns.
        return from_arrow(pq.read_table(path))

    return _read_files(paths, reader)


def read_csv(paths) -> Dataset:
    def reader(path: str):
        import csv

        with open(path) as f:
            rows = list(csv.DictReader(f))
        return _rows_to_block(rows)

    return _read_files(paths, reader)


def read_json(paths) -> Dataset:
    def reader(path: str):
        import json

        with open(path) as f:
            rows = [json.loads(line) for line in f if line.strip()]
        return _rows_to_block(rows)

    return _read_files(paths, reader)


def read_text(paths) -> Dataset:
    """One row per line, column ``text`` (reference: ``read_text``)."""
    def reader(path: str):
        with open(path) as f:
            lines = [line.rstrip("\r\n") for line in f]  # CRLF-safe
        return {"text": np.asarray(lines, dtype=object)}

    return _read_files(paths, reader)


def read_numpy(paths, column: str = "data") -> Dataset:
    """One .npy file per block (reference: ``read_numpy``)."""
    def reader(path: str):
        return {column: np.load(path, allow_pickle=False)}

    return _read_files(paths, reader)


def from_pandas(df, num_blocks: int = 8) -> Dataset:
    """A pandas DataFrame -> column-block Dataset (reference:
    ``from_pandas``)."""
    return from_numpy({c: df[c].to_numpy() for c in df.columns},
                      num_blocks=num_blocks)


def from_arrow(table, num_blocks: int = 8) -> Dataset:
    """A pyarrow Table -> column-block Dataset (reference:
    ``from_arrow``)."""
    return from_numpy(
        {name: table[name].to_numpy(zero_copy_only=False)
         for name in table.column_names}, num_blocks=num_blocks)


def read_binary_files(paths, include_paths: bool = False) -> Dataset:
    """One row per file with its raw ``bytes`` (reference:
    ``read_binary_files`` / ``datasource/binary_datasource.py``)."""
    def reader(path: str):
        with open(path, "rb") as f:
            data = f.read()
        block: Dict[str, Any] = {"bytes": np.array([data], dtype=object)}
        if include_paths:
            block["path"] = np.array([path])
        return block

    return _read_files(paths, reader)


def read_images(paths, size: Optional[tuple] = None,
                mode: str = "RGB", include_paths: bool = False) -> Dataset:
    """Decode image files into an ``image`` tensor column (reference:
    ``read_images`` / ``datasource/image_datasource.py``). ``size``
    resizes to (H, W) — on TPU you almost always want the static shape."""
    def reader(path: str):
        from PIL import Image

        img = Image.open(path).convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        arr = np.asarray(img)
        block: Dict[str, Any] = {"image": arr[None, ...]}
        if include_paths:
            block["path"] = np.array([path])
        return block

    return _read_files(paths, reader)


_CRC32C_TABLE: Optional[List[int]] = None


try:  # a C-speed wheel when one exists; per-byte Python otherwise
    from crc32c import crc32c as _crc32c_native  # type: ignore
except ImportError:
    try:
        from google_crc32c import value as _crc32c_native  # type: ignore
    except ImportError:
        _crc32c_native = None


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli, reflected poly 0x82F63B78). `zlib.crc32` is
    the WRONG polynomial (IEEE): TensorFlow verifies the length-CRC
    unconditionally, so only real CRC32C interoperates. Uses a crc32c
    wheel when installed; falls back to a table-driven pure-Python loop
    (fine for small records, slow for MB-scale payloads)."""
    if _crc32c_native is not None:
        return _crc32c_native(data) & 0xFFFFFFFF
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        # NB: plain `range` here would hit this module's Dataset-factory
        # `range()` shadowing the builtin.
        table = []
        for i in builtins.range(256):
            c = i
            for _ in builtins.range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    tab = _CRC32C_TABLE
    crc = 0xFFFFFFFF
    for b in data:
        crc = tab[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _tfrecord_crc(data: bytes) -> int:
    """Masked CRC32C exactly as the TFRecord spec defines it — files we
    write round-trip through standard TFRecord readers and vice versa."""
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def _tfrecord_crc_legacy(data: bytes) -> int:
    """Masked crc32 (zlib) — what this repo's writer emitted before the
    CRC32C fix; the reader still ACCEPTS it so old files stay readable."""
    import zlib

    crc = zlib.crc32(data) & 0xFFFFFFFF
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def read_tfrecords(paths, verify: bool = False) -> Dataset:
    """Read TFRecord containers into one ``bytes``-typed ``record`` row
    per record (reference: ``read_tfrecords`` — there each record is
    parsed as tf.train.Example; without TF in the image the payload stays
    raw bytes for the caller's proto parser). Wire format: u64 length,
    u32 masked length-crc, payload, u32 masked payload-crc."""
    import struct as _struct

    def reader(path: str):
        records = []
        file_size = __import__("os").path.getsize(path)
        with open(path, "rb") as f:
            while True:
                header = f.read(8)
                if len(header) < 8:
                    break
                (length,) = _struct.unpack("<Q", header)
                lcrc = f.read(4)
                if len(lcrc) < 4:
                    raise ValueError(f"truncated TFRecord file {path}")
                # The length field is attacker/corruption-controlled: a
                # flipped bit must produce a clean error, not a 2^60-byte
                # read. Bound by the file size, and check the length-crc
                # (that is what it exists for) before trusting it.
                if length > file_size:
                    raise ValueError(
                        f"TFRecord length {length} exceeds file size in "
                        f"{path} (corrupt length field)")
                if verify:
                    (want,) = _struct.unpack("<I", lcrc)
                    if (_tfrecord_crc(header) != want
                            and _tfrecord_crc_legacy(header) != want):
                        raise ValueError(
                            f"TFRecord length-crc mismatch in {path}")
                payload = f.read(length)
                pcrc = f.read(4)
                if len(payload) < length or len(pcrc) < 4:
                    raise ValueError(f"truncated TFRecord file {path}")
                if verify:
                    (want,) = _struct.unpack("<I", pcrc)
                    if (_tfrecord_crc(payload) != want
                            and _tfrecord_crc_legacy(payload) != want):
                        raise ValueError(
                            f"TFRecord crc mismatch in {path}")
                records.append(payload)
        return {"record": np.array(records, dtype=object)}

    return _read_files(paths, reader)
