"""Streaming distributed datasets on object-store blocks.

Analogue of the reference's Ray Data core (``data/dataset.py``:
``map_batches`` :368, ``iter_batches`` :3599, ``streaming_split`` :1211,
``materialize`` :4479 over the lazy logical plan + ``StreamingExecutor``,
``_internal/execution/streaming_executor.py:48``): a ``Dataset`` is a lazy
chain of operators over *blocks* (dicts of numpy column arrays) stored as
object refs; execution streams blocks through tasks with a bounded in-flight
window (backpressure), so datasets larger than memory flow through the
shared-memory store block by block.

TPU-relevant adaptation: batch iteration can pad/bucket to static shapes
(``iter_batches(..., pad_to=...)``) because XLA recompiles on shape change —
the reference's dynamic tail batches are an anti-pattern on TPU.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Block,
    Schema,
    block_len as _block_len,
    block_nbytes as _block_nbytes,
    concat_blocks as _concat_blocks,
    slice_block as _slice_block,
    wrap_batch_fn,
)


# ----------------------------------------- shuffle/repartition exchanges

@ray_tpu.remote
def _count_block(block: Block) -> int:
    return _block_len(block)


@ray_tpu.remote
def _slice_for_ranges(block: Block, offset: int, bounds: List[int]):
    """Map half of the repartition exchange: this block covers global rows
    [offset, offset+n); emit its intersection with each output range."""
    n = _block_len(block)
    out = []
    for lo, hi in zip(bounds, bounds[1:]):
        s = max(lo - offset, 0)
        e = min(hi - offset, n)
        out.append(_slice_block(block, s, max(s, e)))
    return tuple(out) if len(out) != 1 else out[0]


@ray_tpu.remote
def _concat_parts(*parts: Block) -> Block:
    live = [p for p in parts if _block_len(p)]
    if not live:
        return {k: v[:0] for k, v in parts[0].items()} if parts else {}
    return _concat_blocks(live)


@ray_tpu.remote
def _shuffle_scatter(block: Block, num_parts: int, seed: int):
    """Map half of the shuffle exchange: scatter rows to partitions."""
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, num_parts, _block_len(block))
    out = [{k: v[assign == p] for k, v in block.items()}
           for p in range(num_parts)]
    return tuple(out) if num_parts != 1 else out[0]


@ray_tpu.remote
def _shuffle_combine(seed: int, *parts: Block) -> Block:
    live = [p for p in parts if _block_len(p)]
    if not live:
        return {k: v[:0] for k, v in parts[0].items()} if parts else {}
    block = _concat_blocks(live)
    perm = np.random.default_rng(seed).permutation(_block_len(block))
    return {k: v[perm] for k, v in block.items()}


@ray_tpu.remote
def _sample_keys(block: Block, key: str, cap: int = 128):
    v = block[key]
    if len(v) <= cap:
        return np.asarray(v)
    idx = np.random.default_rng(0).choice(len(v), cap, replace=False)
    return np.asarray(v)[idx]


@ray_tpu.remote
def _range_scatter(block: Block, key: str, boundaries):
    """Map half of the sort exchange: route rows to range partitions by
    searchsorted against the sampled quantile boundaries."""
    assign = np.searchsorted(boundaries, block[key], side="right")
    out = [{k: v[assign == p] for k, v in block.items()}
           for p in range(len(boundaries) + 1)]
    return tuple(out) if len(out) != 1 else out[0]


@ray_tpu.remote
def _sorted_combine(key: str, descending: bool, *parts: Block) -> Block:
    live = [p for p in parts if _block_len(p)]
    if not live:
        return {k: v[:0] for k, v in parts[0].items()} if parts else {}
    block = _concat_blocks(live)
    order = np.argsort(block[key], kind="stable")
    if descending:
        order = order[::-1]
    return {k: v[order] for k, v in block.items()}


@ray_tpu.remote
def _hash_scatter(block: Block, key: str, num_parts: int):
    """Map half of the groupby exchange: hash-partition rows on the key so
    equal keys land in the same reduce partition."""
    keys = block[key]
    if keys.dtype.kind in "US":
        # Deterministic across processes (Python hash() is seed-randomized
        # per interpreter; scatter tasks run in different workers).
        import zlib

        hashes = np.array([zlib.crc32(str(x).encode()) for x in
                           keys.tolist()], np.int64)
    else:
        hashes = keys.astype(np.int64, copy=False)
    assign = np.abs(hashes) % num_parts
    out = [{k: v[assign == p] for k, v in block.items()}
           for p in range(num_parts)]
    return tuple(out) if num_parts != 1 else out[0]


_AGG_FNS = {
    "count": lambda v: len(v),
    "sum": np.sum,
    "min": np.min,
    "max": np.max,
    "mean": np.mean,
    "std": lambda v: np.std(v, ddof=1) if len(v) > 1 else 0.0,
}


@ray_tpu.remote
def _group_combine(key: str, aggs, *parts: Block) -> Block:
    """Reduce half of the groupby exchange: group this partition's rows by
    key and compute the aggregate columns. ``aggs``: [(kind, col, out)]."""
    live = [p for p in parts if _block_len(p)]
    if not live:
        empty = {key: np.empty(0)}
        empty.update({out: np.empty(0) for _kind, _c, out in aggs})
        return empty
    block = _concat_blocks(live)
    keys = block[key]
    order = np.argsort(keys, kind="stable")
    keys_sorted = keys[order]
    uniq, starts = np.unique(keys_sorted, return_index=True)
    bounds = list(starts) + [len(keys_sorted)]
    out_cols: Dict[str, list] = {out: [] for _kind, _c, out in aggs}
    for gi in range(len(uniq)):
        rows = order[bounds[gi]:bounds[gi + 1]]
        for kind, col, out in aggs:
            vals = block[col][rows] if col is not None else rows
            out_cols[out].append(_AGG_FNS[kind](vals))
    result = {key: uniq}
    result.update({out: np.asarray(v) for out, v in out_cols.items()})
    return result


@ray_tpu.remote
def _map_groups_part(key: str, fn_blob: bytes, *parts: Block) -> Block:
    from ray_tpu.core import serialization

    fn = serialization.loads_function(fn_blob)
    live = [p for p in parts if _block_len(p)]
    if not live:
        return parts[0] if parts else {}
    block = _concat_blocks(live)
    keys = block[key]
    order = np.argsort(keys, kind="stable")
    keys_sorted = keys[order]
    uniq, starts = np.unique(keys_sorted, return_index=True)
    bounds = list(starts) + [len(keys_sorted)]
    outs = []
    for gi in range(len(uniq)):
        rows = order[bounds[gi]:bounds[gi + 1]]
        outs.append(fn({k: v[rows] for k, v in block.items()}))
    return _concat_blocks(outs) if outs else block


@ray_tpu.remote
def _zip_blocks(left: Block, right: Block) -> Block:
    merged = dict(left)
    for k, v in right.items():
        name, i = k, 1
        while name in merged:
            name = f"{k}_{i}"
            i += 1
        merged[name] = v
    return merged


@ray_tpu.remote
def _head_block(block: Block, n: int) -> Block:
    return _slice_block(block, 0, n)


# ----------------------------------------------------------------- plan

class _Op:
    """Logical operator: transforms a stream of blocks."""

    def apply_block(self, block: Block) -> Optional[Block]:
        raise NotImplementedError


class _MapBatches(_Op):
    def __init__(self, fn: Callable[[Block], Block], compute: str = "tasks",
                 concurrency: int = 2, fn_constructor_args: tuple = ()):
        self.fn = fn
        self.compute = compute  # "tasks" | "actors"
        self.concurrency = concurrency
        self.fn_constructor_args = fn_constructor_args

    def apply_block(self, block):
        return self.fn(block)


class _ActorMapWorker:
    """Stateful map worker: a callable-class op instantiates ONCE per actor
    (reference: ``actor_pool_map_operator.py`` — the pattern for expensive
    per-worker setup like loading a model for batch inference)."""

    def __init__(self, fn_blob: bytes, fn_constructor_args: tuple):
        import inspect

        from ray_tpu.core import serialization

        fn = serialization.loads_function(fn_blob)
        self._fn = (fn(*fn_constructor_args) if inspect.isclass(fn)
                    else fn)

    def apply(self, block: Block) -> Block:
        return self._fn(block)


class _Filter(_Op):
    def __init__(self, pred: Callable[[Dict[str, Any]], bool]):
        self.pred = pred

    def apply_block(self, block):
        n = _block_len(block)
        keep = np.array([self.pred({k: v[i] for k, v in block.items()})
                         for i in range(n)], dtype=bool)
        return {k: v[keep] for k, v in block.items()}


def _stage_desc(ops: List[_Op]) -> str:
    """Stable per-stage task name: execution stats aggregate task events
    by this desc (reference: per-operator stats in _internal/stats.py)."""
    names = "+".join(type(op).__name__.lstrip("_") for op in ops) or "Read"
    return f"data::{names}"


def _fuse_ops(ops: List[_Op]) -> Callable[[Block], Block]:
    """Operator fusion: one task applies the whole chain to a block
    (the reference's physical-plan fusion rule — MapOperator chaining)."""

    def fused(block: Block) -> Block:
        for op in ops:
            block = op.apply_block(block)
        return block

    fused.__qualname__ = _stage_desc(ops)
    return fused


class Dataset:
    """Lazy dataset: input block refs + a chain of operators."""

    def __init__(self, block_refs: List[Any], ops: Optional[List[_Op]] = None,
                 exec_log: Optional[List[str]] = None):
        self._block_refs = list(block_refs)
        self._ops = list(ops or [])
        # Stage descs this dataset's lineage has EXECUTED (stats() joins
        # them against the cluster's task events for per-op wall times).
        self._exec_log: List[str] = list(exec_log or [])

    # ---------------------------------------------------- transformations

    def map_batches(self, fn: Callable[[Block], Block],
                    compute: str = "tasks", concurrency=2,
                    fn_constructor_args: tuple = (),
                    batch_format: str = "numpy",
                    **_compat) -> "Dataset":
        """``compute="actors"`` runs this op on a pool of stateful actors;
        ``concurrency`` is a fixed size or a ``(min, max)`` autoscaling
        range (reference: ``ActorPoolStrategy(min_size, max_size)``); ``fn``
        may be a callable CLASS constructed once per actor.
        ``batch_format`` selects what ``fn`` sees: ``"numpy"`` (dict of
        column arrays, the canonical zero-copy block), ``"pyarrow"``
        (``pa.Table``) or ``"pandas"`` (``pd.DataFrame``); the return value
        may be any of the three."""
        from ray_tpu.data.block import BATCH_FORMATS

        if batch_format not in BATCH_FORMATS:
            raise ValueError(f"batch_format must be one of {BATCH_FORMATS}, "
                             f"got {batch_format!r}")
        if batch_format != "numpy":
            import inspect

            if inspect.isclass(fn):
                # Wrap the *instance* call, preserving once-per-actor
                # construction semantics.
                orig_cls = fn

                class _Formatted:
                    def __init__(self, *a):
                        self._wrapped = wrap_batch_fn(orig_cls(*a),
                                                      batch_format)

                    def __call__(self, block):
                        return self._wrapped(block)

                fn = _Formatted
            else:
                fn = wrap_batch_fn(fn, batch_format)
        return Dataset(self._block_refs, self._ops + [_MapBatches(
            fn, compute, concurrency, fn_constructor_args)],
                       exec_log=self._exec_log)

    def map(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]]) -> "Dataset":
        def batch_fn(block: Block) -> Block:
            rows = [fn({k: v[i] for k, v in block.items()})
                    for i in range(_block_len(block))]
            if not rows:
                return block
            return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}

        return self.map_batches(batch_fn)

    def filter(self, pred: Callable[[Dict[str, Any]], bool]) -> "Dataset":
        return Dataset(self._block_refs, self._ops + [_Filter(pred)],
                       exec_log=self._exec_log)

    def repartition(self, num_blocks: int) -> "Dataset":
        """Task-based repartition exchange: map tasks slice each block by
        global row range, reduce tasks concatenate — the driver only touches
        refs, never rows (reference: ``_internal/planner/exchange/``)."""
        mat = self.materialize()
        if not mat._block_refs:
            return mat
        counts = ray_tpu.get([_count_block.remote(r)
                              for r in mat._block_refs])
        total = sum(counts)
        if total == 0:
            return mat
        per = math.ceil(total / num_blocks)
        bounds = [min(i * per, total) for i in range(num_blocks + 1)]
        parts = []  # parts[b][p] = ref to the slice of block b for output p
        offset = 0
        for ref, count in zip(mat._block_refs, counts):
            out = _slice_for_ranges.options(
                num_returns=num_blocks,
                inline_results=False).remote(ref, offset, bounds)
            parts.append(out if isinstance(out, list) else [out])
            offset += count
        live = [p for p, (lo, hi) in enumerate(zip(bounds, bounds[1:]))
                if hi > lo]
        out_refs = [
            _concat_parts.remote(*[parts[b][p]
                                   for b in range(len(parts))])
            for p in live]
        return Dataset(out_refs, exec_log=self._exec_log)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        """Distributed all-to-all shuffle: map tasks scatter each block's
        rows to P partitions at random; reduce tasks concatenate and permute
        within the partition. No rows ever land on the driver (reference:
        the shuffle exchange, ``_internal/planner/exchange/
        shuffle_task_scheduler.py``); O(dataset) memory total stays spread
        over the cluster's stores."""
        mat = self.materialize()
        num_parts = len(mat._block_refs)
        if num_parts == 0:
            return mat
        if seed is None:  # unseeded shuffles must differ run to run
            seed = int(np.random.SeedSequence().entropy % (2 ** 31))
        base_seed = seed
        parts = []
        for i, ref in enumerate(mat._block_refs):
            out = _shuffle_scatter.options(num_returns=num_parts,
                                         inline_results=False).remote(
                ref, num_parts, base_seed + 7919 * i)
            parts.append(out if isinstance(out, list) else [out])
        out_refs = [
            _shuffle_combine.remote(base_seed + 104729 * p,
                                    *[parts[b][p]
                                      for b in range(len(parts))])
            for p in range(num_parts)]
        return Dataset(out_refs, exec_log=self._exec_log)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed sort via a range-partition exchange: sample keys,
        pick quantile boundaries, scatter rows to range partitions, sort
        each partition locally — globally ordered by block index, no rows
        on the driver (reference: ``_internal/planner/exchange/
        sort_task_spec.py`` SortTaskSpec sample->boundaries->exchange)."""
        mat = self.materialize()
        num_parts = len(mat._block_refs)
        if num_parts <= 1:
            if not mat._block_refs:
                return mat
            out = _sorted_combine.remote(key, descending, mat._block_refs[0])
            return Dataset([out], exec_log=self._exec_log)
        samples = np.concatenate(ray_tpu.get(
            [_sample_keys.remote(r, key) for r in mat._block_refs]))
        if len(samples) == 0:
            return mat
        # Order-statistic boundaries (not np.quantile: no interpolation, so
        # string/order-only key dtypes sort too).
        samples = np.sort(samples)
        idx = np.linspace(0, len(samples) - 1,
                          num_parts + 1)[1:-1].astype(int)
        boundaries = samples[idx]
        parts = []
        for ref in mat._block_refs:
            out = _range_scatter.options(num_returns=num_parts,
                                       inline_results=False).remote(
                ref, key, boundaries)
            parts.append(out if isinstance(out, list) else [out])
        order = range(num_parts - 1, -1, -1) if descending else range(
            num_parts)
        out_refs = [
            _sorted_combine.remote(key, descending,
                                   *[parts[b][p] for b in range(len(parts))])
            for p in order]
        return Dataset(out_refs, exec_log=self._exec_log)

    def groupby(self, key: str) -> "GroupedData":
        """Hash-partition exchange + per-partition grouping (reference:
        ``Dataset.groupby`` -> aggregate exchange)."""
        return GroupedData(self, key)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of two datasets with identical row counts; the
        right side is repartitioned to the left's block layout and block
        pairs merge in tasks (duplicate columns get a ``_1`` suffix,
        reference: ``Dataset.zip``)."""
        left = self.materialize()
        counts = ray_tpu.get([_count_block.remote(r)
                              for r in left._block_refs])
        right = other.materialize()
        r_counts = ray_tpu.get([_count_block.remote(r)
                                for r in right._block_refs])
        if sum(counts) != sum(r_counts):
            raise ValueError(
                f"zip needs equal row counts ({sum(counts)} vs "
                f"{sum(r_counts)})")
        # Repartition the right side to the left's exact row boundaries.
        bounds = [0]
        for c in counts:
            bounds.append(bounds[-1] + c)
        parts = []
        offset = 0
        n_out = len(counts)
        for ref, count in zip(right._block_refs, r_counts):
            out = _slice_for_ranges.options(num_returns=n_out,
                                          inline_results=False).remote(
                ref, offset, bounds)
            parts.append(out if isinstance(out, list) else [out])
            offset += count
        right_refs = [
            _concat_parts.remote(*[parts[b][p] for b in range(len(parts))])
            for p in range(n_out)]
        return Dataset(exec_log=self._exec_log, block_refs=[_zip_blocks.remote(l, r) for l, r in
                        zip(left._block_refs, right_refs)])

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets (block-level, zero data movement)."""
        refs = list(self.materialize()._block_refs)
        for other in others:
            refs.extend(other.materialize()._block_refs)
        return Dataset(refs)

    def limit(self, n: int) -> "Dataset":
        """First ``n`` rows; trailing blocks are dropped unread, the
        boundary block is sliced in a task."""
        mat = self.materialize()
        counts = ray_tpu.get([_count_block.remote(r)
                              for r in mat._block_refs])
        refs, have = [], 0
        for ref, count in zip(mat._block_refs, counts):
            if have + count <= n:
                refs.append(ref)
                have += count
            else:
                if n - have > 0:
                    refs.append(_head_block.remote(ref, n - have))
                break
        return Dataset(refs)

    def schema(self) -> Optional[Schema]:
        """Arrow-typed schema from the first block (reference:
        ``Dataset.schema()``): iterable of names, ``[name] -> (np dtype,
        element shape)``, ``.types[name]`` -> Arrow type."""
        for block in self._streamed_blocks(max_in_flight=1):
            return Schema(block)
        return None

    # ------------------------------------------------- global aggregates

    def _column_agg(self, kind: str, col: str):
        if self._has_actor_ops():
            # Actor-pool ops can't run inside the plain fused-task path.
            return self.materialize()._column_agg(kind, col)
        fused = _fuse_ops(self._ops) if self._ops else None

        def part(block: Block):
            if fused is not None:
                block = fused(block)
            v = block[col]
            if len(v) == 0:
                return None
            return (_AGG_FNS[kind](v), len(v), float(np.sum(v)))

        task = ray_tpu.remote(part)
        outs = [o for o in ray_tpu.get(
            [task.remote(r) for r in self._block_refs]) if o is not None]
        if not outs:
            return None
        vals = [o[0] for o in outs]
        if kind == "sum":
            return np.sum(vals)
        if kind == "min":
            return np.min(vals)
        if kind == "max":
            return np.max(vals)
        if kind == "mean":  # weighted by block size
            total_rows = sum(o[1] for o in outs)
            return sum(o[2] for o in outs) / total_rows
        raise ValueError(kind)

    def sum(self, col: str):
        return self._column_agg("sum", col)

    def min(self, col: str):
        return self._column_agg("min", col)

    def max(self, col: str):
        return self._column_agg("max", col)

    def mean(self, col: str):
        return self._column_agg("mean", col)

    def stats(self) -> str:
        """Human-readable execution summary (reference:
        ``Dataset.stats()`` backed by per-operator stats,
        ``_internal/stats.py``): block count, rows, bytes, the pending
        operator chain, and PER-EXECUTED-STAGE wall-time aggregates
        (count/total/mean/p50/p99 + scheduling latency) joined from the
        cluster's task events by stage desc."""
        counts = ray_tpu.get([_count_block.remote(r)
                              for r in self._block_refs])
        sizer = ray_tpu.remote(
            lambda b: int(sum(v.nbytes for v in b.values())))
        sizes = ray_tpu.get([sizer.remote(r) for r in self._block_refs])
        ops = " -> ".join(type(op).__name__.lstrip("_")
                          for op in self._ops) or "Read"
        lines = [f"Dataset: {len(self._block_refs)} blocks, "
                 f"{sum(counts)} rows, {sum(sizes) / 1e6:.2f} MB "
                 f"(pending ops: {ops})"]
        for stage, row in self._stage_stats().items():
            lines.append(
                f"  stage {stage}: {row['tasks']} tasks, wall "
                f"total={row['total_s']:.2f}s mean={row['mean_s'] * 1e3:.0f}ms "
                f"p50={row['p50_s'] * 1e3:.0f}ms p99={row['p99_s'] * 1e3:.0f}ms, "
                f"sched p50={row['sched_p50_ms']:.0f}ms")
        return "\n".join(lines)

    def _stage_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-executed-stage aggregates from the controller's task-event
        table (the same events `ray_tpu timeline` exports)."""
        if not self._exec_log:
            return {}
        try:
            from ray_tpu.core.runtime import get_core_worker

            core = get_core_worker()
            core._flush_task_events()
            events = core.controller.call("list_task_events", 20000)
        except Exception:
            return {}
        out: Dict[str, Dict[str, float]] = {}
        for stage in self._exec_log:
            runs, scheds = [], []
            for e in events:
                if e.get("desc") != stage or e.get("state") != "FINISHED":
                    continue
                if e.get("end_ts") and e.get("lease_ts"):
                    runs.append(e["end_ts"] - e["lease_ts"])
                if e.get("lease_ts") and e.get("submitted_ts"):
                    scheds.append(e["lease_ts"] - e["submitted_ts"])
            if not runs:
                continue
            runs.sort()
            out[stage] = {
                "tasks": len(runs),
                "total_s": float(np.sum(runs)),
                "mean_s": float(np.mean(runs)),
                "p50_s": runs[len(runs) // 2],
                "p99_s": runs[min(len(runs) - 1, int(len(runs) * 0.99))],
                "sched_p50_ms": (1e3 * sorted(scheds)[len(scheds) // 2]
                                 if scheds else 0.0),
            }
        return out

    # --------------------------------------------------------- execution

    def _has_actor_ops(self) -> bool:
        return any(isinstance(op, _MapBatches) and op.compute == "actors"
                   for op in self._ops)

    @staticmethod
    def _memory_budget_bytes() -> int:
        """Streaming-window memory budget: a fraction of the local node's
        FREE object-store capacity (reference: the streaming executor's
        resource-manager budget, ``execution/resource_manager.py`` — the
        window must shrink when the store is tight, not use a constant)."""
        try:
            from ray_tpu.core.runtime import get_core_worker

            core = get_core_worker()
            info = core.clients.get(core.node_addr).call("get_info",
                                                         timeout=5.0)
            free = info["store_capacity_bytes"] - info["store_used_bytes"]
            return max(32 * 1024 * 1024, free // 4)
        except Exception:
            return 256 * 1024 * 1024

    def _streamed_blocks(self,
                         max_in_flight: Optional[int] = None
                         ) -> Iterator[Block]:
        """Pull-based streaming execution with a memory-aware in-flight
        window (the backpressure half of the reference's StreamingExecutor):
        the window targets ``budget / block_bytes`` blocks, sized after the
        first block and clamped to [2, 32]."""
        if self._has_actor_ops():
            # Actor segments materialize via the pool executor.
            for ref in self.materialize()._block_refs:
                yield ray_tpu.get(ref)
            return
        if not self._ops:
            for ref in self._block_refs:
                yield ray_tpu.get(ref)
            return
        import uuid as _uuid

        fused = _fuse_ops(self._ops)
        fused.__qualname__ += f"#{_uuid.uuid4().hex[:6]}"
        self._exec_log.append(fused.__qualname__)
        del self._exec_log[:-20]  # bounded: epoch loops re-execute forever
        process = ray_tpu.remote(fused)
        ref_iter = iter(self._block_refs)
        pending: List[Any] = []
        fixed = max_in_flight is not None
        window = max_in_flight if fixed else 2

        def refill():
            while len(pending) < window:
                try:
                    pending.append(process.remote(next(ref_iter)))
                except StopIteration:
                    return

        refill()
        sized = fixed
        while pending:
            block = ray_tpu.get(pending.pop(0))
            if not sized:
                sized = True
                size = max(1, _block_nbytes(block))
                window = int(np.clip(
                    self._memory_budget_bytes() // size, 2, 32))
            refill()
            yield block

    def materialize(self) -> "Dataset":
        if not self._ops:
            return Dataset(self._block_refs, exec_log=self._exec_log)
        refs = list(self._block_refs)
        # Consecutive task ops fuse into one task per block; an actor op
        # breaks fusion and runs on a stateful pool (operator grouping, as
        # the reference's physical planner does).
        segment: List[_Op] = []
        executed: List[str] = list(self._exec_log)

        def flush_tasks(refs):
            if not segment:
                return refs
            import uuid as _uuid

            fused = _fuse_ops(list(segment))
            # Unique per EXECUTION: stats() joins task events by this
            # desc, and two datasets running the same op chain must not
            # pollute each other's aggregates.
            fused.__qualname__ += f"#{_uuid.uuid4().hex[:6]}"
            executed.append(fused.__qualname__)
            del executed[:-20]  # bounded lineage (epoch loops)
            process = ray_tpu.remote(fused)
            segment.clear()
            return [process.remote(r) for r in refs]

        for op in self._ops:
            if isinstance(op, _MapBatches) and op.compute == "actors":
                refs = flush_tasks(refs)
                refs = self._actor_map(op, refs)
                # Actor-pool stages run through actor calls, which do not
                # land in the task-event table under a stage desc — no
                # exec-log entry (stats() would join the wrong events).
            else:
                segment.append(op)
        refs = flush_tasks(refs)
        ray_tpu.wait(refs, num_returns=len(refs), timeout=None)
        return Dataset(refs, exec_log=executed)

    def _actor_map(self, op: "_MapBatches", refs: List[Any]) -> List[Any]:
        """Actor-pool execution with min/max autoscaling (reference:
        ``actor_pool_map_operator.py`` + ``ActorPoolStrategy(min_size,
        max_size)``): start ``min`` workers, submit with bounded per-actor
        in-flight, and add workers (up to ``max``) while a backlog remains.
        Results stay as refs — the data plane never routes through the
        driver."""
        from ray_tpu.core import serialization

        if isinstance(op.concurrency, (tuple, list)):
            min_size, max_size = op.concurrency
        else:
            min_size = max_size = int(op.concurrency)
        min_size = max(1, min_size)
        max_size = max(min_size, max_size)
        per_actor_in_flight = 2

        worker_cls = ray_tpu.remote(_ActorMapWorker)
        fn_blob = serialization.dumps_function(op.fn)
        actors = [worker_cls.options(num_cpus=1).remote(
            fn_blob, op.fn_constructor_args) for _ in range(min_size)]
        try:
            out_refs: List[Any] = [None] * len(refs)
            in_flight: Dict[Any, int] = {}  # result ref -> actor index
            load = [0] * len(actors)
            queue = list(enumerate(refs))
            while queue or in_flight:
                # Scale up: backlog beyond what the pool can absorb.
                backlog = len(queue) - sum(
                    per_actor_in_flight - l for l in load if
                    l < per_actor_in_flight)
                if backlog > 0 and len(actors) < max_size:
                    actors.append(worker_cls.options(num_cpus=1).remote(
                        fn_blob, op.fn_constructor_args))
                    load.append(0)
                # Submit to the least-loaded actors up to the cap.
                while queue:
                    ai = min(range(len(actors)), key=lambda i: load[i])
                    if load[ai] >= per_actor_in_flight:
                        break
                    i, ref = queue.pop(0)
                    out = actors[ai].apply.remote(ref)
                    out_refs[i] = out
                    in_flight[out] = ai
                    load[ai] += 1
                if in_flight:
                    ready, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                            timeout=None)
                    for r in ready:
                        load[in_flight.pop(r)] -= 1
            self.last_actor_pool_size = len(actors)
            return out_refs
        finally:
            for actor in actors:
                try:
                    ray_tpu.kill(actor)
                except Exception:
                    import logging

                    from ray_tpu.util.ratelimit import log_every

                    # A map actor that survives this kill keeps its
                    # resources leased until the cluster reaps it.
                    log_every("dataset.actor_kill", 10.0,
                              logging.getLogger(__name__),
                              "kill of dataset map actor failed",
                              exc_info=True)

    # -------------------------------------------------------- consumption

    def iter_batches(self, batch_size: int = 256,
                     drop_last: bool = False,
                     pad_to: Optional[int] = None) -> Iterator[Block]:
        """Stream fixed-size batches. ``pad_to`` pads the final partial batch
        to a static size (repeating rows) — static shapes for XLA."""
        carry: Optional[Block] = None
        for block in self._streamed_blocks():
            if carry is not None:
                block = _concat_blocks([carry, block])
                carry = None
            n = _block_len(block)
            start = 0
            while n - start >= batch_size:
                yield _slice_block(block, start, start + batch_size)
                start += batch_size
            if start < n:
                carry = _slice_block(block, start, n)
        if carry is not None and not drop_last:
            if pad_to:
                n = _block_len(carry)
                reps = math.ceil(pad_to / n)
                carry = {k: np.concatenate([v] * reps)[:pad_to]
                         for k, v in carry.items()}
            yield carry

    def iter_device_batches(self, batch_size: int = 256, *, mesh=None,
                            rules=None, prefetch: int = 2,
                            drop_last: bool = False) -> Iterator[Block]:
        """``iter_batches`` + device-side prefetch (VERDICT r4 Missing #5;
        reference ``prefetch_batches``, ``dataset.py:3599``): a background
        thread pads the next batch to the static ``batch_size`` and
        ``device_put``s it (mesh-sharded when ``mesh`` is given) while the
        caller's jitted step runs — fetch wait leaves the step budget."""
        from ray_tpu.data.ingest import device_prefetch

        return device_prefetch(
            self.iter_batches(batch_size, drop_last=drop_last,
                              pad_to=batch_size),
            mesh=mesh, rules=rules, prefetch=prefetch)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._streamed_blocks():
            for i in range(_block_len(block)):
                yield {k: v[i] for k, v in block.items()}

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        return list(itertools.islice(self.iter_rows(), n))

    def count(self) -> int:
        counter = ray_tpu.remote(lambda block: _block_len(block))
        if self._ops:
            fused = _fuse_ops(self._ops)
            counter = ray_tpu.remote(lambda block: _block_len(fused(block)))
        return sum(ray_tpu.get([counter.remote(r) for r in self._block_refs]))

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def _write_blocks(self, path: str, ext: str, write_block) -> List[str]:
        """One output file per block via tasks (the shared write fan-out
        behind write_parquet/csv/json); returns the written paths."""
        import os

        os.makedirs(path, exist_ok=True)
        mat = self.materialize()
        task = ray_tpu.remote(write_block)
        refs = [task.remote(r, os.path.join(path, f"part-{i:05d}.{ext}"))
                for i, r in enumerate(mat._block_refs)]
        return ray_tpu.get(refs)

    def write_parquet(self, path: str) -> List[str]:
        """Write one parquet file per block via tasks (reference:
        ``Dataset.write_parquet``); returns the written paths."""
        def write_one(block: Block, out_path: str) -> str:
            import pyarrow.parquet as pq

            # Tensor-aware conversion (block.py to_arrow): ndim>1 columns
            # become FixedSizeList with shape metadata, so e.g. stacked
            # observations round-trip through parquet (plain pa.table()
            # rejects multi-dimensional numpy columns).
            from ray_tpu.data.block import to_arrow

            pq.write_table(to_arrow(block), out_path)
            return out_path

        return self._write_blocks(path, "parquet", write_one)

    def write_csv(self, path: str) -> List[str]:
        """One CSV file per block via tasks (reference:
        ``Dataset.write_csv``)."""
        def write_one(block: Block, out_path: str) -> str:
            import csv

            cols = list(block.keys())
            with open(out_path, "w", newline="") as f:
                w = csv.writer(f)
                w.writerow(cols)
                for i in range(_block_len(block)):
                    w.writerow([block[c][i] for c in cols])
            return out_path

        return self._write_blocks(path, "csv", write_one)

    def write_json(self, path: str) -> List[str]:
        """One JSON-lines file per block via tasks (reference:
        ``Dataset.write_json``)."""
        def write_one(block: Block, out_path: str) -> str:
            import json

            cols = list(block.keys())
            with open(out_path, "w") as f:
                for i in range(_block_len(block)):
                    row = {c: block[c][i] for c in cols}
                    f.write(json.dumps(
                        {k: (v.item() if hasattr(v, "item") else v)
                         for k, v in row.items()}) + "\n")
            return out_path

        return self._write_blocks(path, "json", write_one)

    def write_tfrecords(self, path: str, column: str = "record"
                        ) -> List[str]:
        """One TFRecord container per block; rows of ``column`` must be
        bytes (reference: ``Dataset.write_tfrecords`` — payloads are the
        caller's serialized protos). Framing matches ``read_tfrecords``."""
        def write_one(block: Block, out_path: str) -> str:
            import struct as _struct

            from ray_tpu.data.read_api import _tfrecord_crc

            with open(out_path, "wb") as f:
                for rec in block[column]:
                    payload = bytes(rec)
                    header = _struct.pack("<Q", len(payload))
                    f.write(header)
                    f.write(_struct.pack("<I", _tfrecord_crc(header)))
                    f.write(payload)
                    f.write(_struct.pack("<I", _tfrecord_crc(payload)))
            return out_path

        return self._write_blocks(path, "tfrecords", write_one)

    def split(self, n: int) -> List["Dataset"]:
        """Split into n datasets by whole blocks."""
        chunks: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(self._block_refs):
            chunks[i % n].append(ref)
        return [Dataset(c, self._ops, exec_log=self._exec_log)
                for c in chunks]

    def streaming_split(self, n: int, equal: bool = True) -> List["DataIterator"]:
        """Per-consumer iterators for distributed ingest (reference:
        ``streaming_split`` feeding Train workers, ``data_config.py:112``).
        Blocks are assigned round-robin by a coordinator actor so consumers
        pull independently and in parallel."""
        coordinator = _SplitCoordinator.options(num_cpus=0).remote(
            self._block_refs, n)
        fused = _fuse_ops(self._ops) if self._ops else None
        return [DataIterator(coordinator, i, fused) for i in range(n)]


class GroupedData:
    """Grouped view of a Dataset (reference: ``GroupedData`` in
    ``data/grouped_data.py``): hash-exchange rows on the key, then compute
    per-group aggregates or apply ``map_groups`` per partition."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _exchange(self):
        mat = self._ds.materialize()
        num_parts = max(1, len(mat._block_refs))
        parts = []
        for ref in mat._block_refs:
            out = _hash_scatter.options(num_returns=num_parts,
                                      inline_results=False).remote(
                ref, self._key, num_parts)
            parts.append(out if isinstance(out, list) else [out])
        return parts, num_parts

    def aggregate(self, *aggs: Tuple[str, Optional[str], str]) -> Dataset:
        """``aggs``: (kind, column, output_name) with kind in
        count/sum/min/max/mean/std. Returns a Dataset with one row per
        group."""
        for kind, _col, _out in aggs:
            if kind not in _AGG_FNS:
                raise ValueError(f"unknown aggregate {kind!r}")
        parts, num_parts = self._exchange()
        if not parts:
            return Dataset([])
        out_refs = [
            _group_combine.remote(self._key, list(aggs),
                                  *[parts[b][p] for b in range(len(parts))])
            for p in range(num_parts)]
        return Dataset(out_refs, exec_log=self._ds._exec_log)

    def count(self) -> Dataset:
        return self.aggregate(("count", None, "count"))

    def sum(self, col: str) -> Dataset:
        return self.aggregate(("sum", col, f"sum({col})"))

    def min(self, col: str) -> Dataset:
        return self.aggregate(("min", col, f"min({col})"))

    def max(self, col: str) -> Dataset:
        return self.aggregate(("max", col, f"max({col})"))

    def mean(self, col: str) -> Dataset:
        return self.aggregate(("mean", col, f"mean({col})"))

    def std(self, col: str) -> Dataset:
        return self.aggregate(("std", col, f"std({col})"))

    def map_groups(self, fn: Callable[[Block], Block]) -> Dataset:
        """Apply ``fn`` to each group's rows (as one block); groups of one
        key never span partitions thanks to the hash exchange."""
        from ray_tpu.core import serialization

        parts, num_parts = self._exchange()
        if not parts:
            return Dataset([])
        fn_blob = serialization.dumps_function(fn)
        out_refs = [
            _map_groups_part.remote(self._key, fn_blob,
                                    *[parts[b][p] for b in range(len(parts))])
            for p in range(num_parts)]
        return Dataset(out_refs, exec_log=self._ds._exec_log)


@ray_tpu.remote
class _SplitCoordinator:
    def __init__(self, block_refs: List[Any], n: int):
        self._queues: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(block_refs):
            self._queues[i % n].append(ref)

    def next_block(self, consumer: int):
        queue = self._queues[consumer]
        return queue.pop(0) if queue else None


class DataIterator:
    def __init__(self, coordinator, index: int, fused):
        self._coordinator = coordinator
        self._index = index
        self._fused = fused

    def iter_batches(self, batch_size: int = 256,
                     drop_last: bool = False,
                     pad_to: Optional[int] = None) -> Iterator[Block]:
        carry: Optional[Block] = None
        while True:
            ref = ray_tpu.get(
                self._coordinator.next_block.remote(self._index))
            if ref is None:
                break
            block = ray_tpu.get(ref)
            if self._fused is not None:
                block = self._fused(block)
            if carry is not None:
                block = _concat_blocks([carry, block])
                carry = None
            n = _block_len(block)
            start = 0
            while n - start >= batch_size:
                yield _slice_block(block, start, start + batch_size)
                start += batch_size
            if start < n:
                carry = _slice_block(block, start, n)
        if carry is not None and not drop_last:
            if pad_to:
                n = _block_len(carry)
                reps = math.ceil(pad_to / n)
                carry = {k: np.concatenate([v] * reps)[:pad_to]
                         for k, v in carry.items()}
            yield carry

    def iter_device_batches(self, batch_size: int = 256, *, mesh=None,
                            rules=None, prefetch: int = 2,
                            drop_last: bool = False) -> Iterator[Block]:
        """Per-worker device-prefetched ingest (see
        ``Dataset.iter_device_batches``): the form train loops consume via
        ``train.get_dataset_shard(...)``."""
        from ray_tpu.data.ingest import device_prefetch

        return device_prefetch(
            self.iter_batches(batch_size, drop_last=drop_last,
                              pad_to=batch_size),
            mesh=mesh, rules=rules, prefetch=prefetch)
