"""Streaming distributed datasets on object-store blocks.

Analogue of the reference's Ray Data core (``data/dataset.py``:
``map_batches`` :368, ``iter_batches`` :3599, ``streaming_split`` :1211,
``materialize`` :4479 over the lazy logical plan + ``StreamingExecutor``,
``_internal/execution/streaming_executor.py:48``): a ``Dataset`` is a lazy
chain of operators over *blocks* (dicts of numpy column arrays) stored as
object refs; execution streams blocks through tasks with a bounded in-flight
window (backpressure), so datasets larger than memory flow through the
shared-memory store block by block.

TPU-relevant adaptation: batch iteration can pad/bucket to static shapes
(``iter_batches(..., pad_to=...)``) because XLA recompiles on shape change —
the reference's dynamic tail batches are an anti-pattern on TPU.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

import ray_tpu

Block = Dict[str, np.ndarray]


def _block_len(block: Block) -> int:
    if not block:
        return 0
    return len(next(iter(block.values())))


def _concat_blocks(blocks: List[Block]) -> Block:
    keys = blocks[0].keys()
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def _slice_block(block: Block, start: int, end: int) -> Block:
    return {k: v[start:end] for k, v in block.items()}


# ----------------------------------------------------------------- plan

class _Op:
    """Logical operator: transforms a stream of blocks."""

    def apply_block(self, block: Block) -> Optional[Block]:
        raise NotImplementedError


class _MapBatches(_Op):
    def __init__(self, fn: Callable[[Block], Block]):
        self.fn = fn

    def apply_block(self, block):
        return self.fn(block)


class _Filter(_Op):
    def __init__(self, pred: Callable[[Dict[str, Any]], bool]):
        self.pred = pred

    def apply_block(self, block):
        n = _block_len(block)
        keep = np.array([self.pred({k: v[i] for k, v in block.items()})
                         for i in range(n)], dtype=bool)
        return {k: v[keep] for k, v in block.items()}


def _fuse_ops(ops: List[_Op]) -> Callable[[Block], Block]:
    """Operator fusion: one task applies the whole chain to a block
    (the reference's physical-plan fusion rule — MapOperator chaining)."""

    def fused(block: Block) -> Block:
        for op in ops:
            block = op.apply_block(block)
        return block

    return fused


class Dataset:
    """Lazy dataset: input block refs + a chain of operators."""

    def __init__(self, block_refs: List[Any], ops: Optional[List[_Op]] = None):
        self._block_refs = list(block_refs)
        self._ops = list(ops or [])

    # ---------------------------------------------------- transformations

    def map_batches(self, fn: Callable[[Block], Block],
                    **_compat) -> "Dataset":
        return Dataset(self._block_refs, self._ops + [_MapBatches(fn)])

    def map(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]]) -> "Dataset":
        def batch_fn(block: Block) -> Block:
            rows = [fn({k: v[i] for k, v in block.items()})
                    for i in range(_block_len(block))]
            if not rows:
                return block
            return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}

        return self.map_batches(batch_fn)

    def filter(self, pred: Callable[[Dict[str, Any]], bool]) -> "Dataset":
        return Dataset(self._block_refs, self._ops + [_Filter(pred)])

    def repartition(self, num_blocks: int) -> "Dataset":
        mat = self.materialize()
        blocks = [ray_tpu.get(r) for r in mat._block_refs]
        if not blocks:
            return mat
        whole = _concat_blocks(blocks)
        n = _block_len(whole)
        per = math.ceil(n / num_blocks)
        refs = [ray_tpu.put(_slice_block(whole, i * per,
                                         min((i + 1) * per, n)))
                for i in range(num_blocks) if i * per < n]
        return Dataset(refs)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        """Global shuffle: permute rows across all blocks (the reference's
        all-to-all shuffle exchange, simplified to a gather-permute —
        sufficient below the multi-node scale)."""
        mat = self.materialize()
        blocks = [ray_tpu.get(r) for r in mat._block_refs]
        if not blocks:
            return mat
        whole = _concat_blocks(blocks)
        n = _block_len(whole)
        rng = np.random.default_rng(seed)
        perm = rng.permutation(n)
        shuffled = {k: v[perm] for k, v in whole.items()}
        per = max(1, math.ceil(n / max(1, len(mat._block_refs))))
        refs = [ray_tpu.put(_slice_block(shuffled, i, min(i + per, n)))
                for i in range(0, n, per)]
        return Dataset(refs)

    # --------------------------------------------------------- execution

    def _streamed_blocks(self, max_in_flight: int = 8) -> Iterator[Block]:
        """Pull-based streaming execution with a bounded in-flight window
        (the backpressure half of the reference's StreamingExecutor)."""
        if not self._ops:
            for ref in self._block_refs:
                yield ray_tpu.get(ref)
            return
        fused = _fuse_ops(self._ops)
        process = ray_tpu.remote(lambda block: fused(block))
        pending: List[Any] = []
        refs = iter(self._block_refs)
        for ref in itertools.islice(refs, max_in_flight):
            pending.append(process.remote(ref))
        for ref in refs:
            yield ray_tpu.get(pending.pop(0))
            pending.append(process.remote(ref))
        for p in pending:
            yield ray_tpu.get(p)

    def materialize(self) -> "Dataset":
        if not self._ops:
            return Dataset(self._block_refs)
        fused = _fuse_ops(self._ops)
        process = ray_tpu.remote(lambda block: fused(block))
        out_refs = [process.remote(ref) for ref in self._block_refs]
        ray_tpu.wait(out_refs, num_returns=len(out_refs), timeout=None)
        return Dataset(out_refs)

    # -------------------------------------------------------- consumption

    def iter_batches(self, batch_size: int = 256,
                     drop_last: bool = False,
                     pad_to: Optional[int] = None) -> Iterator[Block]:
        """Stream fixed-size batches. ``pad_to`` pads the final partial batch
        to a static size (repeating rows) — static shapes for XLA."""
        carry: Optional[Block] = None
        for block in self._streamed_blocks():
            if carry is not None:
                block = _concat_blocks([carry, block])
                carry = None
            n = _block_len(block)
            start = 0
            while n - start >= batch_size:
                yield _slice_block(block, start, start + batch_size)
                start += batch_size
            if start < n:
                carry = _slice_block(block, start, n)
        if carry is not None and not drop_last:
            if pad_to:
                n = _block_len(carry)
                reps = math.ceil(pad_to / n)
                carry = {k: np.concatenate([v] * reps)[:pad_to]
                         for k, v in carry.items()}
            yield carry

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._streamed_blocks():
            for i in range(_block_len(block)):
                yield {k: v[i] for k, v in block.items()}

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        return list(itertools.islice(self.iter_rows(), n))

    def count(self) -> int:
        counter = ray_tpu.remote(lambda block: _block_len(block))
        if self._ops:
            fused = _fuse_ops(self._ops)
            counter = ray_tpu.remote(lambda block: _block_len(fused(block)))
        return sum(ray_tpu.get([counter.remote(r) for r in self._block_refs]))

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def split(self, n: int) -> List["Dataset"]:
        """Split into n datasets by whole blocks."""
        chunks: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(self._block_refs):
            chunks[i % n].append(ref)
        return [Dataset(c, self._ops) for c in chunks]

    def streaming_split(self, n: int, equal: bool = True) -> List["DataIterator"]:
        """Per-consumer iterators for distributed ingest (reference:
        ``streaming_split`` feeding Train workers, ``data_config.py:112``).
        Blocks are assigned round-robin by a coordinator actor so consumers
        pull independently and in parallel."""
        coordinator = _SplitCoordinator.options(num_cpus=0).remote(
            self._block_refs, n)
        fused = _fuse_ops(self._ops) if self._ops else None
        return [DataIterator(coordinator, i, fused) for i in range(n)]


@ray_tpu.remote
class _SplitCoordinator:
    def __init__(self, block_refs: List[Any], n: int):
        self._queues: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(block_refs):
            self._queues[i % n].append(ref)

    def next_block(self, consumer: int):
        queue = self._queues[consumer]
        return queue.pop(0) if queue else None


class DataIterator:
    def __init__(self, coordinator, index: int, fused):
        self._coordinator = coordinator
        self._index = index
        self._fused = fused

    def iter_batches(self, batch_size: int = 256,
                     drop_last: bool = False) -> Iterator[Block]:
        carry: Optional[Block] = None
        while True:
            ref = ray_tpu.get(
                self._coordinator.next_block.remote(self._index))
            if ref is None:
                break
            block = ray_tpu.get(ref)
            if self._fused is not None:
                block = self._fused(block)
            if carry is not None:
                block = _concat_blocks([carry, block])
                carry = None
            n = _block_len(block)
            start = 0
            while n - start >= batch_size:
                yield _slice_block(block, start, start + batch_size)
                start += batch_size
            if start < n:
                carry = _slice_block(block, start, n)
        if carry is not None and not drop_last:
            yield carry
