"""ray_tpu.data: streaming distributed datasets (reference: Ray Data)."""

from ray_tpu.data.dataset import (  # noqa: F401
    DataIterator,
    Dataset,
    GroupedData,
)
from ray_tpu.data.read_api import (  # noqa: F401
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
    read_tfrecords,
)
