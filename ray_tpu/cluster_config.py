"""Cluster launch configuration: YAML schema + validation.

Analogue of the reference's cluster YAML + ``ray-schema.json``
(``python/ray/autoscaler/ray-schema.json``; loaded/validated in
``autoscaler/_private/util.py`` ``prepare_config``/``validate_config``),
reduced to the fields the TPU-era launcher actually uses:

.. code-block:: yaml

    cluster_name: demo
    provider:
      type: fake_multinode        # or: tpu_vm
      project_id: my-project      # tpu_vm only
      zone: us-central2-b         # tpu_vm only
      accelerator_type: v5litepod-16
      runtime_version: v2-alpha-tpuv5-lite
    min_workers: 0
    max_workers: 8
    idle_timeout_minutes: 5
    head:
      resources: {CPU: 4}
    worker:
      resources: {CPU: 4, TPU: 4}
      labels: {pool: tpu}
    auth:                          # tpu_vm only (command runner)
      ssh_user: ray
      ssh_private_key: ~/.ssh/id_rsa
    setup_commands:
      - pip install -e .
    dry_run: false                 # tpu_vm: record API/SSH calls, no egress

Unknown top-level keys are rejected (typo protection — the reference's
jsonschema does the same via ``additionalProperties: false``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class ConfigError(ValueError):
    """Invalid cluster config; message carries the YAML path."""


_TOP_KEYS = {"cluster_name", "provider", "min_workers", "max_workers",
             "idle_timeout_minutes", "head", "worker", "auth",
             "setup_commands", "dry_run"}
_PROVIDER_TYPES = {"fake_multinode", "tpu_vm"}


@dataclass
class ProviderConfig:
    type: str = "fake_multinode"
    project_id: Optional[str] = None
    zone: Optional[str] = None
    accelerator_type: str = "v5litepod-16"
    runtime_version: str = "v2-alpha-tpuv5-lite"


@dataclass
class NodeTypeConfig:
    resources: Dict[str, float] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class AuthConfig:
    ssh_user: str = "ray"
    ssh_private_key: Optional[str] = None


@dataclass
class ClusterConfig:
    cluster_name: str = "ray-tpu"
    provider: ProviderConfig = field(default_factory=ProviderConfig)
    min_workers: int = 0
    max_workers: int = 8
    idle_timeout_minutes: float = 5.0
    head: NodeTypeConfig = field(default_factory=NodeTypeConfig)
    worker: NodeTypeConfig = field(default_factory=NodeTypeConfig)
    auth: AuthConfig = field(default_factory=AuthConfig)
    setup_commands: List[str] = field(default_factory=list)
    dry_run: bool = False


def _require(cond: bool, path: str, msg: str) -> None:
    if not cond:
        raise ConfigError(f"{path}: {msg}")


def _mapping(value: Any, path: str) -> Dict:
    _require(isinstance(value, dict), path,
             f"expected a mapping, got {type(value).__name__}")
    return value


def _resources(value: Any, path: str) -> Dict[str, float]:
    value = _mapping(value, path)
    out = {}
    for k, v in value.items():
        _require(isinstance(k, str), f"{path}.{k}", "resource keys are "
                 "strings")
        _require(isinstance(v, (int, float)) and v >= 0, f"{path}.{k}",
                 f"resource amounts are non-negative numbers, got {v!r}")
        out[k] = float(v)
    return out


def validate_config(raw: Dict[str, Any]) -> ClusterConfig:
    raw = _mapping(raw, "<root>")
    unknown = set(raw) - _TOP_KEYS
    _require(not unknown, "<root>",
             f"unknown keys {sorted(unknown)} (valid: {sorted(_TOP_KEYS)})")

    cfg = ClusterConfig()
    if "cluster_name" in raw:
        _require(isinstance(raw["cluster_name"], str) and raw["cluster_name"],
                 "cluster_name", "must be a non-empty string")
        cfg.cluster_name = raw["cluster_name"]

    prov = _mapping(raw.get("provider", {}), "provider")
    ptype = prov.get("type", "fake_multinode")
    _require(ptype in _PROVIDER_TYPES, "provider.type",
             f"must be one of {sorted(_PROVIDER_TYPES)}, got {ptype!r}")
    cfg.provider = ProviderConfig(
        type=ptype,
        project_id=prov.get("project_id"),
        zone=prov.get("zone"),
        accelerator_type=prov.get("accelerator_type", "v5litepod-16"),
        runtime_version=prov.get("runtime_version", "v2-alpha-tpuv5-lite"),
    )
    if ptype == "tpu_vm":
        _require(bool(cfg.provider.project_id), "provider.project_id",
                 "required for tpu_vm")
        _require(bool(cfg.provider.zone), "provider.zone",
                 "required for tpu_vm")

    for key in ("min_workers", "max_workers"):
        if key in raw:
            _require(isinstance(raw[key], int) and raw[key] >= 0, key,
                     f"must be a non-negative integer, got {raw[key]!r}")
            setattr(cfg, key, raw[key])
    _require(cfg.min_workers <= cfg.max_workers, "min_workers",
             f"min_workers ({cfg.min_workers}) exceeds max_workers "
             f"({cfg.max_workers})")
    if "idle_timeout_minutes" in raw:
        v = raw["idle_timeout_minutes"]
        _require(isinstance(v, (int, float)) and v >= 0,
                 "idle_timeout_minutes", f"must be >= 0, got {v!r}")
        cfg.idle_timeout_minutes = float(v)

    for section in ("head", "worker"):
        if section in raw:
            sec = _mapping(raw[section], section)
            unknown = set(sec) - {"resources", "labels"}
            _require(not unknown, section, f"unknown keys {sorted(unknown)}")
            node = NodeTypeConfig()
            if "resources" in sec:
                node.resources = _resources(sec["resources"],
                                            f"{section}.resources")
            if "labels" in sec:
                labels = _mapping(sec["labels"], f"{section}.labels")
                node.labels = {str(k): str(v) for k, v in labels.items()}
            setattr(cfg, section, node)

    if "auth" in raw:
        sec = _mapping(raw["auth"], "auth")
        unknown = set(sec) - {"ssh_user", "ssh_private_key"}
        _require(not unknown, "auth", f"unknown keys {sorted(unknown)}")
        cfg.auth = AuthConfig(
            ssh_user=sec.get("ssh_user", "ray"),
            ssh_private_key=sec.get("ssh_private_key"))

    if "setup_commands" in raw:
        cmds = raw["setup_commands"]
        _require(isinstance(cmds, list)
                 and all(isinstance(c, str) for c in cmds),
                 "setup_commands", "must be a list of strings")
        cfg.setup_commands = list(cmds)

    if "dry_run" in raw:
        _require(isinstance(raw["dry_run"], bool), "dry_run",
                 "must be a boolean")
        cfg.dry_run = raw["dry_run"]
    return cfg


def load_config(path: str) -> ClusterConfig:
    import yaml

    with open(os.path.expanduser(path)) as f:
        raw = yaml.safe_load(f)
    _require(isinstance(raw, dict), path, "cluster YAML must be a mapping")
    return validate_config(raw)
