version = "0.1.0"
