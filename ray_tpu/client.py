"""Thin-client protocol: drive a cluster from outside it.

Analogue of the reference's Ray Client (``python/ray/util/client/`` +
``ray_client.proto``; design doc ``util/client/ARCHITECTURE.md``):
``ray_tpu.init(address="ray-tpu://host:port")`` connects a *thin* client —
the local process never joins the cluster, owns no objects, and needs only
one outbound TCP connection (NAT/laptop friendly). A :class:`ClientServer`
running inside the cluster hosts the real driver state: it owns every
object/actor the client creates and proxies get/put/task/actor calls.

Where the reference runs one proxied driver *process* per client, sessions
here share the hosting process's core worker (a design choice the
serverless runtime allows); per-session bookkeeping still scopes cleanup —
disconnecting releases the session's object refs and kills its unnamed
actors, exactly like a departing driver.

Client-side refs/handles pickle into resolver calls
(``__reduce__`` -> :func:`_resolve_ref`), so arbitrarily nested refs in
task args rebuild into real refs server-side during deserialization — no
argument-tree walking.
"""

from __future__ import annotations

import logging
import threading
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.errors import RayTpuError
from ray_tpu.core.rpc import RpcClient, RpcServer
from ray_tpu.util.ratelimit import log_every

logger = logging.getLogger(__name__)

Addr = Tuple[str, int]

# ------------------------------------------------------------------ server

_resolving = threading.local()  # .session set while deserializing a request


def _resolve_ref(ref_id: bytes):
    session = getattr(_resolving, "session", None)
    if session is None:
        raise RayTpuError("client ref deserialized outside a client session")
    ref = session.refs.get(ref_id)
    if ref is None:
        raise RayTpuError(f"client ref {ref_id.hex()} unknown "
                          f"(released or from another session)")
    return ref


def _resolve_actor(actor_key: str):
    session = getattr(_resolving, "session", None)
    if session is None:
        raise RayTpuError("client actor handle deserialized outside a session")
    handle = session.actors.get(actor_key)
    if handle is None:
        raise RayTpuError(f"client actor {actor_key} unknown")
    return handle


class _Session:
    def __init__(self):
        self.refs: Dict[bytes, Any] = {}      # ref id -> real ObjectRef
        self.actors: Dict[str, Any] = {}      # actor key -> real handle
        self.named_actors: set = set()        # keys NOT killed on disconnect
        self.lock = threading.Lock()
        import time

        self.last_seen = time.monotonic()


class ClientServer:
    """Hosts thin-client sessions inside the cluster.

    Runs wherever a driver can run (head process, a dedicated
    ``python -m ray_tpu.client_server`` process via :func:`serve`, or a
    test). Uses the hosting process's core worker, which must be
    initialized first.
    """

    def __init__(self, host: str = "0.0.0.0"):
        from ray_tpu.core.runtime import get_core_worker

        self._core = get_core_worker()
        if self._core is None:
            raise RayTpuError("ClientServer requires ray_tpu.init() first")
        self._sessions: Dict[str, _Session] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._server = RpcServer(
            handlers={
                "client_connect": self._connect,
                "client_disconnect": self._disconnect,
                "client_put": self._put,
                "client_get": self._get,
                "client_wait": self._wait,
                "client_task": self._task,
                "client_actor_create": self._actor_create,
                "client_actor_call": self._actor_call,
                "client_get_actor": self._get_named_actor,
                "client_kill": self._kill,
                "client_release": self._release,
                "client_cluster_resources": self._cluster_resources,
                "client_ping": self._ping,
                "ping": lambda: "pong",
            },
            host=host,
            name="client-server",
            max_workers=64,
        )
        self.address: Addr = self._server.addr
        # Crashed clients never call disconnect: reap sessions whose
        # keepalive went quiet (the reference's proxied driver dies when the
        # client's data channel drops).
        self._reaper = threading.Thread(
            target=self._reap_loop, name="client-session-reaper", daemon=True)
        self._reaper.start()

    # -- session plumbing

    def _session(self, sid: str) -> _Session:
        import time

        with self._lock:
            session = self._sessions.get(sid)
            if session is None:
                raise RayTpuError(f"unknown client session {sid}")
            session.last_seen = time.monotonic()
            return session

    def _ping(self, sid: str) -> bool:
        self._session(sid)
        return True

    def _reap_loop(self) -> None:
        import time

        from ray_tpu.core.config import config

        while not self._stopped.wait(5.0):
            cutoff = time.monotonic() - config.client_session_timeout_s
            with self._lock:
                stale = [sid for sid, s in self._sessions.items()
                         if s.last_seen < cutoff]
            for sid in stale:
                self._disconnect(sid)

    def _connect(self) -> str:
        sid = uuid.uuid4().hex
        with self._lock:
            self._sessions[sid] = _Session()
        return sid

    def _disconnect(self, sid: str) -> None:
        with self._lock:
            session = self._sessions.pop(sid, None)
        if session is None:
            return
        # A departing driver's unnamed actors die with it; named actors are
        # the reference's detached-ish survivors.
        for key, handle in session.actors.items():
            if key not in session.named_actors:
                try:
                    handle.kill(no_restart=True)
                except Exception:
                    log_every("client.session_actor_kill", 10.0, logger,
                              "killing session actor failed",
                              exc_info=True)
        session.refs.clear()
        session.actors.clear()

    def _deserialize(self, session: _Session, frame: bytes):
        _resolving.session = session
        try:
            return serialization.deserialize(frame)
        finally:
            _resolving.session = None

    def _track(self, session: _Session, ref) -> bytes:
        rid = ref.id.binary()
        with session.lock:
            session.refs[rid] = ref
        return rid

    # -- data plane

    # NOTE: handlers go straight to the core worker, NEVER through
    # ray_tpu.core.api — the api layer routes to the active thin client, so
    # a ClientServer co-hosted with a connected client (tests, notebooks)
    # would recurse over its own RPC.

    def _put(self, sid: str, frame: bytes) -> bytes:
        session = self._session(sid)
        value = self._deserialize(session, frame)
        return self._track(session, self._core.put(value))

    def _get(self, sid: str, ref_ids: List[bytes],
             timeout: Optional[float]) -> Dict[str, Any]:
        session = self._session(sid)
        try:
            refs = [_resolve_with(session, rid) for rid in ref_ids]
            values = self._core.get(refs, timeout)
        except BaseException as e:  # noqa: BLE001 — shipped to the client
            return {"error": serialization.serialize(e)}
        return {"values": serialization.serialize(values)}

    def _wait(self, sid: str, ref_ids: List[bytes], num_returns: int,
              timeout: Optional[float]) -> Tuple[List[bytes], List[bytes]]:
        session = self._session(sid)
        refs = [_resolve_with(session, rid) for rid in ref_ids]
        ready, pending = self._core.wait(refs, num_returns, timeout)
        return ([r.id.binary() for r in ready],
                [r.id.binary() for r in pending])

    def _release(self, sid: str, ref_ids: List[bytes]) -> None:
        try:
            session = self._session(sid)
        except RayTpuError:
            return
        with session.lock:
            for rid in ref_ids:
                session.refs.pop(rid, None)

    # -- tasks / actors

    def _task(self, sid: str, fn_blob: bytes, args_frame: bytes,
              options: Dict[str, Any]) -> List[bytes]:
        from ray_tpu.core.remote_function import RemoteFunction

        session = self._session(sid)
        fn = serialization.loads_function(fn_blob)
        args, kwargs = self._deserialize(session, args_frame)
        refs = RemoteFunction(fn, options).remote(*args, **kwargs)
        refs = refs if isinstance(refs, list) else [refs]
        return [self._track(session, r) for r in refs]

    def _actor_create(self, sid: str, cls_blob: bytes, args_frame: bytes,
                      options: Dict[str, Any]) -> str:
        from ray_tpu.core.actor import ActorClass

        session = self._session(sid)
        cls = serialization.loads_function(cls_blob)
        args, kwargs = self._deserialize(session, args_frame)
        handle = ActorClass(cls, options).remote(*args, **kwargs)
        key = handle._actor_id.hex()
        with session.lock:
            session.actors[key] = handle
            if options.get("name"):
                session.named_actors.add(key)
        return key

    def _actor_call(self, sid: str, actor_key: str, method: str,
                    args_frame: bytes, num_returns: int) -> List[bytes]:
        session = self._session(sid)
        handle = session.actors.get(actor_key)
        if handle is None:
            raise RayTpuError(f"unknown actor {actor_key}")
        args, kwargs = self._deserialize(session, args_frame)
        bound = getattr(handle, method)
        if num_returns != 1:
            bound = bound.options(num_returns=num_returns)
        refs = bound.remote(*args, **kwargs)
        refs = refs if isinstance(refs, list) else [refs]
        return [self._track(session, r) for r in refs]

    def _get_named_actor(self, sid: str, name: str) -> str:
        from ray_tpu.core.actor import get_actor  # core-level, not api

        session = self._session(sid)
        handle = get_actor(name)
        key = handle._actor_id.hex()
        with session.lock:
            session.actors[key] = handle
            session.named_actors.add(key)  # looked up, not owned: never kill
        return key

    def _kill(self, sid: str, actor_key: str, no_restart: bool) -> None:
        session = self._session(sid)
        handle = session.actors.get(actor_key)
        if handle is not None:
            handle.kill(no_restart=no_restart)

    def _cluster_resources(self) -> Dict[str, float]:
        return self._core.controller.call("cluster_resources")

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            sids = list(self._sessions)
        for sid in sids:
            self._disconnect(sid)
        self._server.stop()


def _resolve_with(session: _Session, rid: bytes):
    _resolving.session = session
    try:
        return _resolve_ref(rid)
    finally:
        _resolving.session = None


# ------------------------------------------------------------------ client

_current_client: Optional["ClientCore"] = None


def current_client() -> Optional["ClientCore"]:
    return _current_client


class ClientObjectRef:
    """Client-side surrogate for a server-owned ObjectRef."""

    __slots__ = ("id", "_client", "__weakref__")

    def __init__(self, rid: bytes, client: "ClientCore"):
        self.id = rid
        self._client = client

    def hex(self) -> str:
        return self.id.hex()

    def __reduce__(self):
        # Inside task args shipped to the server, rebuild the REAL ref.
        return (_resolve_ref, (self.id,))

    def __repr__(self) -> str:
        return f"ClientObjectRef({self.id.hex()[:16]})"

    def __hash__(self) -> int:
        return hash(self.id)

    def __eq__(self, other) -> bool:
        return isinstance(other, ClientObjectRef) and other.id == self.id

    def __del__(self):
        client = self._client
        if client is not None:
            client._queue_release(self.id)


class ClientRemoteFunction:
    def __init__(self, fn, options: Dict[str, Any]):
        self._fn = fn
        self._options = dict(options)
        self._blob = serialization.dumps_function(fn)

    def options(self, **overrides) -> "ClientRemoteFunction":
        merged = dict(self._options)
        merged.update(overrides)
        return ClientRemoteFunction(self._fn, merged)

    def remote(self, *args, **kwargs):
        client = current_client()
        if client is None:
            raise RayTpuError("client not connected")
        rids = client._call("client_task", self._blob,
                            client._pack_args(args, kwargs), self._options)
        refs = [ClientObjectRef(rid, client) for rid in rids]
        return refs[0] if self._options.get("num_returns", 1) == 1 else refs

    def __call__(self, *a, **k):
        raise TypeError("Remote function cannot be called directly; "
                        "use .remote().")


class ClientActorMethod:
    def __init__(self, handle: "ClientActorHandle", name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, num_returns: int = 1) -> "ClientActorMethod":
        return ClientActorMethod(self._handle, self._name, num_returns)

    def remote(self, *args, **kwargs):
        client = self._handle._client
        rids = client._call(
            "client_actor_call", self._handle._key, self._name,
            client._pack_args(args, kwargs), self._num_returns)
        refs = [ClientObjectRef(rid, client) for rid in rids]
        return refs[0] if self._num_returns == 1 else refs


class ClientActorHandle:
    def __init__(self, key: str, client: "ClientCore"):
        self._key = key
        self._client = client

    def __getattr__(self, name: str) -> ClientActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self, name)

    def __reduce__(self):
        return (_resolve_actor, (self._key,))

    def __repr__(self) -> str:
        return f"ClientActorHandle({self._key[:16]})"


class ClientActorClass:
    def __init__(self, cls, options: Dict[str, Any]):
        self._cls = cls
        self._options = dict(options)
        self._blob = serialization.dumps_function(cls)

    def options(self, **overrides) -> "ClientActorClass":
        merged = dict(self._options)
        merged.update(overrides)
        return ClientActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        client = current_client()
        if client is None:
            raise RayTpuError("client not connected")
        key = client._call("client_actor_create", self._blob,
                           client._pack_args(args, kwargs), self._options)
        return ClientActorHandle(key, client)


class ClientCore:
    """The thin client itself (what ``init(address="ray-tpu://…")``
    returns). One outbound RPC connection; all state lives server-side."""

    def __init__(self, addr: Addr):
        self._rpc = RpcClient(tuple(addr))
        self._sid = self._rpc.call("client_connect")
        self._released: List[bytes] = []
        self._release_lock = threading.Lock()
        self._closed = False
        # Keepalive: the server reaps sessions whose pings stop (crashed
        # clients). A dedicated connection so pings never queue behind a
        # long blocking get on the main connection.
        self._ping_rpc = RpcClient(tuple(addr))
        self._stop_ping = threading.Event()
        self._ping_thread = threading.Thread(
            target=self._ping_loop, name="client-keepalive", daemon=True)
        self._ping_thread.start()

    def _ping_loop(self) -> None:
        from ray_tpu.core.config import config

        period = max(1.0, config.client_session_timeout_s / 6.0)
        while not self._stop_ping.wait(period):
            try:
                self._ping_rpc.call("client_ping", self._sid, timeout=10.0)
            except Exception:
                # Enough missed pings and the server reaps the session —
                # the user deserves a trail before that happens.
                log_every("client.ping", period * 3, logger,
                          "client keepalive ping failed", exc_info=True)

    # -- plumbing

    def _call(self, method: str, *args, timeout: Optional[float] = None):
        self._flush_releases()
        return self._rpc.call(method, self._sid, *args, timeout=timeout)

    def _pack_args(self, args, kwargs) -> bytes:
        return serialization.serialize((tuple(args), dict(kwargs)))

    def _queue_release(self, rid: bytes) -> None:
        if self._closed:
            return
        with self._release_lock:
            self._released.append(rid)

    def _flush_releases(self) -> None:
        with self._release_lock:
            batch, self._released = self._released, []
        if batch and not self._closed:
            try:
                self._rpc.call("client_release", self._sid, batch)
            except Exception:
                # The dropped batch leaks server-side refs until session
                # teardown — tolerable, but never silent.
                log_every("client.release", 10.0, logger,
                          "releasing %d client refs failed", len(batch),
                          exc_info=True)

    # -- public surface (mirrors core worker usage in api.py)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        batch = [refs] if single else list(refs)
        reply = self._call("client_get", [r.id for r in batch], timeout,
                           timeout=None if timeout is None else timeout + 30)
        if "error" in reply:
            raise serialization.deserialize(reply["error"])
        values = serialization.deserialize(reply["values"])
        return values[0] if single else values

    def put(self, value: Any) -> ClientObjectRef:
        rid = self._call("client_put", serialization.serialize(value))
        return ClientObjectRef(rid, self)

    def wait(self, refs: Sequence[ClientObjectRef], num_returns: int,
             timeout: Optional[float]):
        by_id = {r.id: r for r in refs}
        ready, pending = self._call("client_wait", [r.id for r in refs],
                                    num_returns, timeout)
        return ([by_id[i] for i in ready], [by_id[i] for i in pending])

    def kill(self, handle: ClientActorHandle, no_restart: bool = True):
        self._call("client_kill", handle._key, no_restart)

    def get_actor(self, name: str) -> ClientActorHandle:
        key = self._call("client_get_actor", name)
        return ClientActorHandle(key, self)

    def cluster_resources(self) -> Dict[str, float]:
        return self._rpc.call("client_cluster_resources")

    def disconnect(self) -> None:
        global _current_client
        if self._closed:
            return
        self._closed = True
        self._stop_ping.set()
        try:
            self._rpc.call("client_disconnect", self._sid, timeout=10.0)
        except Exception:
            # Best-effort goodbye; the server reaps the session on ping
            # timeout anyway.
            log_every("client.disconnect", 10.0, logger,
                      "clean disconnect failed", level=logging.INFO,
                      exc_info=True)
        self._rpc.close()
        self._ping_rpc.close()
        if _current_client is self:
            _current_client = None


def connect(address: str, ignore_reinit_error: bool = False) -> ClientCore:
    """Connect this process as a thin client. ``address`` is
    ``ray-tpu://host:port`` of a :class:`ClientServer`."""
    global _current_client
    if _current_client is not None:
        if ignore_reinit_error:
            return _current_client
        raise RayTpuError("already connected as a client; pass "
                          "ignore_reinit_error=True to allow")
    hostport = address[len("ray-tpu://"):]
    host, _, port = hostport.rpartition(":")
    client = ClientCore((host, int(port)))
    _current_client = client
    return client
