"""Distributed FIFO queue backed by an actor.

Analogue of the reference's ``ray.util.queue.Queue``: a named-actor-backed
queue usable from any process in the cluster.
"""

from __future__ import annotations

import collections
import time
from typing import Any, List, Optional

import ray_tpu


class _QueueActor:
    def __init__(self, maxsize: int):
        self._maxsize = maxsize
        self._items = collections.deque()

    def put(self, item, block_token=None) -> bool:
        if self._maxsize > 0 and len(self._items) >= self._maxsize:
            return False
        self._items.append(item)
        return True

    def get_nowait(self):
        if not self._items:
            return (False, None)
        return (True, self._items.popleft())

    def qsize(self) -> int:
        return len(self._items)


class Empty(Exception):
    pass


class Full(Exception):
    pass


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        cls = ray_tpu.remote(_QueueActor)
        self._actor = cls.options(num_cpus=0,
                                  **(actor_options or {})).remote(maxsize)

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self._actor.put.remote(item)):
                return
            if not block or (deadline and time.monotonic() > deadline):
                raise Full()
            time.sleep(0.02)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self._actor.get_nowait.remote())
            if ok:
                return item
            if not block or (deadline and time.monotonic() > deadline):
                raise Empty()
            time.sleep(0.02)

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0
