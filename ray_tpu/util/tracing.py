"""Distributed tracing: span propagation through remote calls + profile
events.

Reference analogues (SURVEY §5.1): the OpenTelemetry task-span wrapper
(``util/tracing/tracing_helper.py`` — spans around ``remote()`` calls with
context propagated in task metadata) and per-task ``profile_event``
instrumentation (``_raylet.pyx:4031`` -> ``TaskEventBuffer``). OTel is not
in this image, so the context itself is native: a (trace_id, span_id) pair
carried by a contextvar, shipped inside task specs, and re-entered on the
executing worker — every task event and profile event records its trace,
so ``ray_tpu timeline`` renders a causally-linked Chrome trace across
processes.

Usage::

    with tracing.trace("ingest"):          # root span on the driver
        ref = f.remote()                   # span ctx rides the task spec

    def f():
        with tracing.profile_event("load-shard"):   # nested timing slice
            ...
"""

from __future__ import annotations

import contextvars
import os
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

# (trace_id_hex, span_id_hex) of the active span, or None.
_ctx: contextvars.ContextVar[Optional[tuple]] = contextvars.ContextVar(
    "ray_tpu_trace", default=None)


def current() -> Optional[tuple]:
    """(trace_id, span_id) of the active span, if any."""
    return _ctx.get()


def traced() -> bool:
    """True when a trace context is active in this task/thread — the
    one-contextvar-read gate hot-ish paths use before building span
    names/attrs (the pipeline stage actors emit fwd/bwd/apply spans
    only while the driver's step span is propagated to them; an
    untraced step pays exactly this read per stage call)."""
    return _ctx.get() is not None


def _new_id() -> str:
    return os.urandom(8).hex()


def context_for_spec() -> Optional[Dict[str, str]]:
    """Serializable span context to embed in an outgoing task spec."""
    cur = _ctx.get()
    if cur is None:
        return None
    return {"trace_id": cur[0], "parent_span": cur[1]}


@contextmanager
def activate(spec_ctx: Optional[Dict[str, str]], name: Optional[str] = None):
    """Worker-side: enter the caller's trace (new child span) for the
    duration of a task's execution. With ``name``, the execution itself
    is recorded as a SPAN parented under the caller's span — the link
    that makes a cross-process trace causally connected (caller-side
    attempt span -> this execution span -> spans the task opens)."""
    if not spec_ctx:
        yield
        return
    span_id = _new_id()
    token = _ctx.set((spec_ctx["trace_id"], span_id))
    start = time.time()
    try:
        yield
    finally:
        _ctx.reset(token)
        if name is not None:
            _record({
                "task_id": span_id,
                "desc": name,
                "state": "SPAN",
                "trace_id": spec_ctx["trace_id"],
                "span_id": span_id,
                "parent_span": spec_ctx.get("parent_span"),
                "lease_ts": start,
                "end_ts": time.time(),
                "attrs": None,
            })


@contextmanager
def resume(ctx: Optional[tuple]):
    """Re-enter a previously captured :func:`current` tuple on another
    thread (e.g. a router pool thread running work submitted under a
    live span). Unlike :func:`activate` this CONTINUES the captured span
    rather than opening a child."""
    if ctx is None:
        yield
        return
    token = _ctx.set(ctx)
    try:
        yield
    finally:
        _ctx.reset(token)


def record_span(name: str, start_ts: float, end_ts: float,
                ctx: Optional[tuple] = None, **attrs: Any) -> Optional[str]:
    """Record a completed span with EXPLICIT wall-clock timestamps,
    parented under ``ctx`` (a captured :func:`current` tuple; defaults
    to the active context). The decode engine uses this to attribute
    work it performed on its own loop thread — queue wait, prefill
    chunks, decode — back to the request's trace after the fact.
    Returns the new span id (None when there is no trace to attach to)."""
    parent = ctx if ctx is not None else _ctx.get()
    if parent is None:
        return None
    span_id = _new_id()
    _record({
        "task_id": span_id,
        "desc": name,
        "state": "SPAN",
        "trace_id": parent[0],
        "span_id": span_id,
        "parent_span": parent[1],
        "lease_ts": start_ts,
        "end_ts": end_ts,
        "attrs": attrs or None,
    })
    return span_id


@contextmanager
def trace(name: str, **attrs: Any):
    """Open a span; the first span in a process starts a new trace. The
    span is recorded as a task event (state=SPAN) so it lands in the
    timeline alongside the tasks it caused."""
    parent = _ctx.get()
    trace_id = parent[0] if parent else _new_id()
    span_id = _new_id()
    token = _ctx.set((trace_id, span_id))
    start = time.time()
    try:
        yield (trace_id, span_id)
    finally:
        _ctx.reset(token)
        _record({
            "task_id": span_id,
            "desc": name,
            "state": "SPAN",
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_span": parent[1] if parent else None,
            "lease_ts": start,
            "end_ts": time.time(),
            "attrs": attrs or None,
        })


@contextmanager
def profile_event(name: str, **attrs: Any):
    """Record a timed slice inside the current task/span (reference:
    ``ray.profiling.profile`` / ``_raylet.pyx profile_event``)."""
    with trace(f"profile:{name}", **attrs):
        yield


def _record(event: Dict[str, Any]) -> None:
    from ray_tpu.core.runtime import get_core_worker

    try:
        core = get_core_worker()
    except Exception:
        core = None  # not connected: spans still nest, just unrecorded
    if core is None:
        return
    cur = _ctx.get()
    if cur is not None:
        event.setdefault("trace_id", cur[0])
    event.setdefault("owner", core.addr)
    event.setdefault("worker", getattr(core, "worker_id", None) and
                     core.worker_id.hex()[:8])
    core.record_task_event(event)


def dump_stacks() -> str:
    """All thread stacks of THIS process, formatted — the py-spy-equivalent
    introspection primitive (reference: dashboard reporter's py-spy shell
    out, ``profile_manager.py:79``; here native via sys._current_frames so
    it needs no external binary or ptrace rights)."""
    import sys
    import threading
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sorted(sys._current_frames().items()):
        out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out)
