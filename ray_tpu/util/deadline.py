"""Deadline: the blessed remaining-time idiom for bounded waits.

Control-plane code that accepts a ``timeout_s`` budget and then makes
SEVERAL blocking calls must not hand the FULL budget to each one — a
three-RPC path with ``timeout_s=30`` threaded raw can park for 90 s,
silently tripling the caller's budget (the deadline-not-propagated
graftlint rule). The fix this module blesses::

    dl = Deadline.after(timeout_s)
    stub.reserve_subslice(owner, chips, timeout=dl.remaining())
    stub.mh_register_group(gid, n, None, owner, timeout=dl.remaining())
    if dl.expired:
        raise ...

``remaining()`` never returns a value a wait primitive would read as
"forever": once the budget is spent it returns ``MIN_WAIT_S`` (a small
positive float), so the next bounded call fires its typed timeout
promptly instead of parking — the terminal state is an exception from
the wait site, never a hang. ``Deadline(None)`` is the explicit
unlimited deadline for callers that genuinely mean forever:
``remaining()`` returns ``None`` and ``expired`` is always False, so a
single code path serves both bounded and unbounded callers.

Sub-budgets: ``dl.sub(5.0)`` returns a child deadline capped at BOTH
5 s and the parent's remaining time — the idiom for "this phase gets at
most 5 s of whatever is left" (e.g. one formation RPC inside a gang
budget). Pure ``time.monotonic`` arithmetic; no threads, no state.
"""

from __future__ import annotations

import time
from typing import Optional

__all__ = ["Deadline", "MIN_WAIT_S"]

# Floor handed to wait primitives once the budget is spent: small enough
# that the timeout fires "now", large enough that a zero/negative value
# never reads as "no timeout" to an API with that convention.
MIN_WAIT_S = 0.001


class Deadline:
    """A fixed point on the monotonic clock; ``remaining()`` shrinks."""

    __slots__ = ("_at",)

    def __init__(self, at: Optional[float]):
        # ``at`` is an absolute time.monotonic() instant (None = never).
        self._at = at

    @classmethod
    def after(cls, timeout_s: Optional[float]) -> "Deadline":
        """Deadline ``timeout_s`` from now (None = unlimited)."""
        if timeout_s is None:
            return cls(None)
        return cls(time.monotonic() + float(timeout_s))

    @property
    def expired(self) -> bool:
        return self._at is not None and time.monotonic() >= self._at

    def remaining(self) -> Optional[float]:
        """Seconds left, floored at MIN_WAIT_S; None when unlimited.

        The floor (instead of 0 / negative) keeps the contract "a
        bounded caller's wait always fires a typed timeout": several
        wait APIs treat 0/None as "poll"/"forever" and a negative
        value as an error.
        """
        if self._at is None:
            return None
        return max(self._at - time.monotonic(), MIN_WAIT_S)

    def sub(self, timeout_s: Optional[float]) -> "Deadline":
        """A child deadline: ``timeout_s`` from now, capped at the
        parent — a phase budget that can never outlive the call's."""
        if timeout_s is None:
            return Deadline(self._at)
        child = time.monotonic() + float(timeout_s)
        return Deadline(child if self._at is None
                        else min(child, self._at))

    def __repr__(self) -> str:
        if self._at is None:
            return "Deadline(unlimited)"
        return f"Deadline(remaining={self.remaining():.3f}s)"
