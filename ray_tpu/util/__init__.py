"""ray_tpu.util: ActorPool, Queue, host-side collectives."""

from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
from ray_tpu.util.queue import Empty, Full, Queue  # noqa: F401
