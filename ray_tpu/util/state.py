"""Python state API (reference: ``ray.util.state`` — ``api.py``:
``list_nodes/list_actors/list_tasks/list_jobs/summarize_tasks``).

The CLI (``python -m ray_tpu list ...``) and dashboard share these same
controller RPCs; this module is the in-process Python surface.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def _controller():
    from ray_tpu.core.runtime import get_core_worker

    return get_core_worker().controller


def list_nodes() -> List[Dict[str, Any]]:
    return _controller().call("list_nodes")


def list_actors() -> List[Dict[str, Any]]:
    return _controller().call("list_actors")


def list_jobs() -> Dict[str, Dict[str, Any]]:
    return _controller().call("list_jobs")


def list_tasks(limit: int = 1000) -> List[Dict[str, Any]]:
    """Recent task state-transition events (FINISHED/FAILED/SPAN...)."""
    return _controller().call("list_task_events", limit)


def node_infos(nodes: Optional[List[Dict[str, Any]]] = None,
               timeout: float = 10.0) -> List[Dict[str, Any]]:
    """Live node-supervisor ``get_info`` for every alive node (the ONE
    per-node poll shared by ``list_objects``, the ``memory`` CLI and the
    dashboard — pass ``nodes`` when the caller already has a node list or
    no core worker, e.g. a standalone controller client). Unreachable
    nodes yield an ``{"error": ...}`` entry rather than disappearing; RPCs
    are bounded so one hung supervisor can't wedge the caller."""
    from ray_tpu.core.rpc import RpcClient

    out = []
    for n in (nodes if nodes is not None else list_nodes()):
        if not n.get("alive"):
            continue
        client = None
        try:
            client = RpcClient(tuple(n["addr"]), connect_timeout=timeout)
            # graftlint: disable=deadline-not-propagated (PER-NODE bound by design: the docstring's contract is that one hung supervisor costs at most `timeout`, not that the whole sweep fits in it — errors fill in for slow nodes, so a Deadline here would starve the tail of a big cluster)
            out.append(client.call("get_info", timeout=timeout))
        except Exception as e:
            out.append({"node_id": n["node_id"], "error": str(e)})
        finally:
            if client is not None:
                client.close()
    return out


def list_objects() -> List[Dict[str, Any]]:
    """Per-node object-store occupancy (the object-level listing the
    reference offers is owner-distributed; store totals are the
    cluster-level view). Unreachable nodes appear with an ``error`` field
    so capacity sums don't silently shrink."""
    out = []
    for info in node_infos():
        if "error" in info:
            out.append({"node_id": info["node_id"],
                        "error": info["error"]})
        else:
            out.append({
                "node_id": info["node_id"],
                "store_used_bytes": info.get("store_used_bytes", 0),
                "store_capacity_bytes": info.get("store_capacity_bytes", 0),
                "spilled_bytes": info.get("spilled_bytes", 0),
            })
    return out


def summarize_tasks(limit: int = 10000) -> Dict[str, Any]:
    """Counts by (desc, state) — reference: ``ray summary tasks``."""
    summary: Dict[str, Dict[str, int]] = {}
    for e in list_tasks(limit):
        desc = e.get("desc") or e.get("task_id", "?")[:8]
        states = summary.setdefault(desc, {})
        state = e.get("state", "?")
        states[state] = states.get(state, 0) + 1
    return {"by_task": summary,
            "total": sum(sum(s.values()) for s in summary.values())}


def cluster_resources() -> Dict[str, float]:
    return _controller().call("cluster_resources")


def available_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in list_nodes():
        if n.get("alive"):
            for k, v in n.get("available", {}).items():
                total[k] = total.get(k, 0.0) + v
    return total
