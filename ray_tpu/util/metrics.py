"""Metrics: Counter / Gauge / Histogram with cluster export.

Analogue of the reference's two-layer metrics pipeline: the user API
(``python/ray/util/metrics.py`` Counter/Gauge/Histogram) and the C++
registry exported to the node agent and on to Prometheus
(``src/ray/stats/metric_defs.cc:44-183``, ``metric_exporter.cc``).
Here: every process has a registry; a daemon flusher pushes snapshots to
the cluster controller (tagged with node/worker identity), which aggregates
them and serves them via the state API (``list_metrics``) and a
Prometheus-text endpoint (``metrics_text``).
"""

from __future__ import annotations

import bisect
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)


class _Registry:
    _instance: Optional["_Registry"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, tuple], Dict[str, Any]] = {}
        self._flusher: Optional[threading.Thread] = None
        # Snapshot-time collectors (see add_collector): hot paths keep
        # plain attribute counters at zero registry cost; these callables
        # publish them (gauges / counter deltas / batched histogram
        # observations) only when a snapshot is actually taken.
        self._collectors: List[Any] = []
        self._collectors_lock = threading.Lock()
        # One process, one pusher: the core-worker flusher owns the push
        # when a runtime is connected; a node's MetricsAgent claims it
        # otherwise. Two pushers shipping the same (cumulative) registry
        # under different source keys would double every counter.
        self._pusher: Optional[str] = None

    @classmethod
    def get(cls) -> "_Registry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def record(self, name: str, kind: str, tags: Dict[str, str],
               value: float, buckets=None) -> None:
        self.record_many(name, kind, tags, (value,), buckets)

    def record_many(self, name: str, kind: str, tags: Dict[str, str],
                    values, buckets=None) -> None:
        """Bulk record under ONE lock acquisition — hot producers (the
        decode engine flushing a step's worth of observations) pay a
        single registry round-trip instead of one per value."""
        key = (name, tuple(sorted(tags.items())))
        with self._lock:
            entry = self._metrics.get(key)
            if entry is None:
                entry = {"name": name, "kind": kind, "tags": dict(tags),
                         "value": 0.0}
                if kind == "histogram":
                    entry["buckets"] = list(buckets or _DEFAULT_BUCKETS)
                    entry["counts"] = [0] * (len(entry["buckets"]) + 1)
                    entry["sum"] = 0.0
                    entry["count"] = 0
                self._metrics[key] = entry
            for value in values:
                if kind == "counter":
                    entry["value"] += value
                elif kind == "gauge":
                    entry["value"] = value
                else:
                    idx = bisect.bisect_left(entry["buckets"], value)
                    entry["counts"][idx] += 1
                    entry["sum"] += value
                    entry["count"] += 1
            self._ensure_flusher()

    def _ensure_flusher(self) -> None:
        if self._flusher is None or not self._flusher.is_alive():
            self._flusher = threading.Thread(
                target=self._flush_loop, name="metrics-flush", daemon=True)
            self._flusher.start()

    def add_collector(self, fn) -> None:
        """Register a snapshot-time collector. Bound methods are held
        weakly so registering never pins its owner (an RpcServer that is
        simply dropped must still be collectable); dead entries are
        pruned at the next snapshot."""
        import weakref

        ref = (weakref.WeakMethod(fn)
               if getattr(fn, "__self__", None) is not None else fn)
        with self._collectors_lock:
            self._collectors.append(ref)

    def _run_collectors(self) -> None:
        import weakref

        with self._collectors_lock:
            refs = list(self._collectors)
        dead = []
        for ref in refs:
            fn = ref() if isinstance(ref, weakref.WeakMethod) else ref
            if fn is None:
                dead.append(ref)
                continue
            try:
                fn()
            except Exception:
                from ray_tpu.util.ratelimit import log_every

                # A broken collector must never take down the flusher;
                # systematically failing ones still leave a trail.
                log_every("metrics.collector", 60.0,
                          logging.getLogger(__name__),
                          "metrics collector failed", exc_info=True)
        if dead:
            with self._collectors_lock:
                for ref in dead:
                    if ref in self._collectors:
                        self._collectors.remove(ref)

    def claim_pusher(self, owner: str) -> bool:
        """First caller per process wins; the core-worker flusher passes
        the reserved name 'core' which always wins (it has the richest
        source identity). A stale 'core' claim is reclaimable once the
        runtime disconnects (driver shutdown in a long-lived node
        process). Returns True when ``owner`` should push."""
        with self._collectors_lock:
            if owner == "core":
                self._pusher = "core"
                return True
            if self._pusher == "core":
                from ray_tpu.core import runtime

                if runtime._core_worker is not None:
                    return False
                self._pusher = owner
                return True
            if self._pusher in (None, owner):
                self._pusher = owner
                return True
            return False

    def release_pusher(self, owner: str) -> None:
        with self._collectors_lock:
            if self._pusher == owner:
                self._pusher = None

    def snapshot(self, run_collectors: bool = True) -> List[Dict[str, Any]]:
        if run_collectors:
            self._run_collectors()
        from ray_tpu.core.config import config as rt_config

        limit = rt_config.metrics_max_series
        with self._lock:
            out = []
            dropped = 0
            for e in self._metrics.values():
                if limit and len(out) >= limit:
                    # Bounded push: a runaway-cardinality producer must
                    # not grow every snapshot RPC without limit. Insertion
                    # order is stable, so established series keep
                    # flowing and the overflow is visible below.
                    dropped += 1
                    continue
                d = dict(e)
                if "counts" in d:
                    # Deep-copy the mutable histogram state: the shallow
                    # dict still aliases the live counts list, and the
                    # flusher serializes this snapshot OUTSIDE the lock.
                    d["counts"] = list(d["counts"])
                    d["buckets"] = list(d["buckets"])
                out.append(d)
            if dropped:
                out.append({"name": "metrics_series_dropped", "kind": "gauge",
                            "tags": {}, "value": float(dropped)})
            return out

    def flush_now(self) -> bool:
        """Push one snapshot to the cluster controller synchronously
        (tests and benches that cannot wait out the flush interval).
        Returns False when no runtime is connected or the push failed."""
        from ray_tpu.core import runtime

        core = runtime._core_worker
        if core is None:
            return False
        self.claim_pusher("core")
        try:
            core.controller.notify("push_metrics", self._source(core),
                                   self.snapshot())
            return True
        except Exception:
            return False

    @staticmethod
    def _source(core) -> Dict[str, Any]:
        return {"node_id": core.node_id.binary(),
                "worker_id": core.worker_id.binary(),
                "role": getattr(core, "mode", "worker"),
                "pid": __import__("os").getpid()}

    def _flush_loop(self) -> None:
        from ray_tpu.core import runtime
        from ray_tpu.core.config import config as rt_config

        while True:
            time.sleep(max(0.1, rt_config.metrics_flush_interval_s))
            core = runtime._core_worker
            if core is None:
                continue
            self.claim_pusher("core")
            try:
                core.controller.notify("push_metrics", self._source(core),
                                       self.snapshot())
            except Exception:
                from ray_tpu.util.ratelimit import log_every

                # Metrics are droppable, but a push that fails every
                # 5 s tick means the head is unreachable — worth a line.
                log_every("metrics.push", 60.0,
                          logging.getLogger(__name__),
                          "metrics push to controller failed",
                          exc_info=True)


def add_collector(fn) -> None:
    """Register a snapshot-time collector on this process's registry.

    The idiom for hot paths: keep plain attribute counters where the
    locks you already hold make them cheap, and publish them (gauge
    sets, counter deltas via :class:`CounterDeltas`, batched histogram
    observations) only when a snapshot is taken — the RPC reactor and
    the decode loop never touch the registry lock."""
    _Registry.get().add_collector(fn)


class CounterDeltas:
    """Publish monotonic plain-int totals as registry counters.

    ``inc_to(counter, key, total, tags)`` increments ``counter`` by the
    growth since the last call for ``key``; a total that went BACKWARDS
    (owner restarted / conn churned) re-bases without emitting, so a
    restart never double-counts. Collector-thread only — no locking."""

    def __init__(self):
        self._last: Dict[Any, float] = {}

    def inc_to(self, counter: "Counter", key: Any, total: float,
               tags: Optional[Dict[str, str]] = None) -> None:
        prev = self._last.get(key, 0.0)
        if total > prev:
            counter.inc(total - prev, tags)
        self._last[key] = total


class _Metric:
    kind = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return merged


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        _Registry.get().record(self._name, "counter", self._tags(tags),
                               value)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        _Registry.get().record(self._name, "gauge", self._tags(tags), value)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = tuple(boundaries or _DEFAULT_BUCKETS)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        _Registry.get().record(self._name, "histogram", self._tags(tags),
                               value, self._boundaries)

    def observe_many(self, values: Sequence[float],
                     tags: Optional[Dict[str, str]] = None) -> None:
        """Record a batch of observations under one registry lock."""
        if values:
            _Registry.get().record_many(self._name, "histogram",
                                        self._tags(tags), values,
                                        self._boundaries)


def prometheus_text(aggregated: Dict[str, Any]) -> str:
    """Render the controller's aggregated metrics as Prometheus exposition
    text (the shape the reference's node agent exposes). Histograms emit
    the full cumulative ``_bucket{le=...}`` ladder (+Inf last) so a real
    Prometheus can compute quantiles with histogram_quantile()."""
    lines: List[str] = []
    for source, metrics in aggregated.items():
        # Cluster source keys are "<node8>/<role>/pid<N>" (controller
        # push_metrics): expose the parts as first-class labels so a
        # Prometheus query can aggregate by node or role directly.
        parts = source.split("/")
        src_tags = {"source": source}
        if len(parts) == 3 and parts[2].startswith("pid"):
            src_tags.update(node=parts[0], role=parts[1], pid=parts[2][3:])
        for m in metrics:
            tags = dict(m.get("tags", {}))
            tags.update(src_tags)
            label = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
            if m["kind"] == "histogram":
                cum = 0
                for edge, n in zip(m["buckets"], m["counts"]):
                    cum += n
                    blabel = (label + "," if label else "") + f'le="{edge}"'
                    lines.append(f'{m["name"]}_bucket{{{blabel}}} {cum}')
                blabel = (label + "," if label else "") + 'le="+Inf"'
                lines.append(f'{m["name"]}_bucket{{{blabel}}} {m["count"]}')
                lines.append(f'{m["name"]}_sum{{{label}}} {m["sum"]}')
                lines.append(f'{m["name"]}_count{{{label}}} {m["count"]}')
            else:
                lines.append(f'{m["name"]}{{{label}}} {m["value"]}')
    return "\n".join(lines) + "\n"


# ------------------------------------------------ aggregation helpers
#
# Shared by serve.status()'s SLO summaries, the dashboard's serve panel
# and the benches: ONE way to merge per-process histogram snapshots and
# read quantiles out of them, so every surface reports the same number.


def merge_histograms(aggregated: Dict[str, List[Dict[str, Any]]],
                     name: str) -> Dict[tuple, Dict[str, Any]]:
    """Merge same-name histogram entries across sources, keyed by their
    tag items. Entries whose bucket boundaries disagree are skipped (the
    metrics-name-collision lint makes that a build failure)."""
    out: Dict[tuple, Dict[str, Any]] = {}
    for metrics in aggregated.values():
        for m in metrics:
            if m.get("name") != name or m.get("kind") != "histogram":
                continue
            key = tuple(sorted(m.get("tags", {}).items()))
            cur = out.get(key)
            if cur is None:
                out[key] = {"name": name, "kind": "histogram",
                            "tags": dict(m.get("tags", {})),
                            "buckets": list(m["buckets"]),
                            "counts": list(m["counts"]),
                            "sum": m["sum"], "count": m["count"]}
            elif cur["buckets"] == list(m["buckets"]):
                cur["counts"] = [a + b for a, b in zip(cur["counts"],
                                                       m["counts"])]
                cur["sum"] += m["sum"]
                cur["count"] += m["count"]
    return out


def histogram_quantile(entry: Dict[str, Any], q: float) -> Optional[float]:
    """Bucket-interpolated quantile of one (merged) histogram entry —
    Prometheus histogram_quantile() semantics: linear within the bucket,
    the top (+Inf) bucket clamps to its lower edge. None when empty.
    Quantiles are bucket-QUANTIZED: precision is the bucket grid, which
    is the documented trade for surviving process death and transport."""
    total = entry.get("count", 0)
    if not total:
        return None
    rank = q * total
    cum = 0
    prev_edge = 0.0
    for edge, n in zip(entry["buckets"], entry["counts"]):
        if cum + n >= rank and n > 0:
            frac = (rank - cum) / n
            return prev_edge + (edge - prev_edge) * max(0.0, min(1.0, frac))
        cum += n
        prev_edge = edge
    return prev_edge  # landed in the +Inf bucket: clamp to the last edge


def histogram_summary(entry: Dict[str, Any]) -> Dict[str, Any]:
    """{count, mean, p50, p99} of one merged histogram entry."""
    count = entry.get("count", 0)
    return {
        "count": count,
        "mean": (entry["sum"] / count) if count else None,
        "p50": histogram_quantile(entry, 0.5),
        "p99": histogram_quantile(entry, 0.99),
    }


def counter_totals(aggregated: Dict[str, List[Dict[str, Any]]],
                   name: str) -> Dict[tuple, float]:
    """Sum same-name counter entries across sources, keyed by tag items."""
    out: Dict[tuple, float] = {}
    for metrics in aggregated.values():
        for m in metrics:
            if m.get("name") == name and m.get("kind") == "counter":
                key = tuple(sorted(m.get("tags", {}).items()))
                out[key] = out.get(key, 0.0) + m.get("value", 0.0)
    return out


def gauge_totals(aggregated: Dict[str, List[Dict[str, Any]]],
                 name: str) -> Dict[tuple, float]:
    """Sum same-name gauge entries across sources, keyed by tag items
    (each source reports its own level; the cluster view is the sum —
    e.g. per-process outbound queue bytes -> cluster queued bytes)."""
    out: Dict[tuple, float] = {}
    for metrics in aggregated.values():
        for m in metrics:
            if m.get("name") == name and m.get("kind") == "gauge":
                key = tuple(sorted(m.get("tags", {}).items()))
                out[key] = out.get(key, 0.0) + m.get("value", 0.0)
    return out


def delta_aggregated(before: Dict[str, List[Dict[str, Any]]],
                     after: Dict[str, List[Dict[str, Any]]]
                     ) -> Dict[str, List[Dict[str, Any]]]:
    """Per-source deltas between two cluster snapshots (the doctor's
    two-sample view): counters and histogram counts become the growth
    over the window (clamped at >= 0 — a restarted producer re-bases
    instead of going negative), gauges keep their AFTER level. Sources
    present only in ``after`` count from zero."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for source, metrics in after.items():
        prev = {(m.get("name"), tuple(sorted(m.get("tags", {}).items()))): m
                for m in before.get(source, [])}
        rows = []
        for m in metrics:
            key = (m.get("name"), tuple(sorted(m.get("tags", {}).items())))
            p = prev.get(key)
            d = dict(m)
            if m.get("kind") == "counter":
                d["value"] = max(0.0, m.get("value", 0.0)
                                 - (p.get("value", 0.0) if p else 0.0))
            elif m.get("kind") == "histogram":
                d["counts"] = list(m["counts"])
                d["buckets"] = list(m["buckets"])
                if p and list(p.get("buckets", [])) == d["buckets"]:
                    d["counts"] = [max(0, a - b) for a, b in
                                   zip(d["counts"], p["counts"])]
                    d["count"] = max(0, m["count"] - p["count"])
                    d["sum"] = max(0.0, m["sum"] - p["sum"])
            rows.append(d)
        out[source] = rows
    return out
