"""Metrics: Counter / Gauge / Histogram with cluster export.

Analogue of the reference's two-layer metrics pipeline: the user API
(``python/ray/util/metrics.py`` Counter/Gauge/Histogram) and the C++
registry exported to the node agent and on to Prometheus
(``src/ray/stats/metric_defs.cc:44-183``, ``metric_exporter.cc``).
Here: every process has a registry; a daemon flusher pushes snapshots to
the cluster controller (tagged with node/worker identity), which aggregates
them and serves them via the state API (``list_metrics``) and a
Prometheus-text endpoint (``metrics_text``).
"""

from __future__ import annotations

import bisect
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)


class _Registry:
    _instance: Optional["_Registry"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, tuple], Dict[str, Any]] = {}
        self._flusher: Optional[threading.Thread] = None

    @classmethod
    def get(cls) -> "_Registry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def record(self, name: str, kind: str, tags: Dict[str, str],
               value: float, buckets=None) -> None:
        key = (name, tuple(sorted(tags.items())))
        with self._lock:
            entry = self._metrics.get(key)
            if entry is None:
                entry = {"name": name, "kind": kind, "tags": dict(tags),
                         "value": 0.0}
                if kind == "histogram":
                    entry["buckets"] = list(buckets or _DEFAULT_BUCKETS)
                    entry["counts"] = [0] * (len(entry["buckets"]) + 1)
                    entry["sum"] = 0.0
                    entry["count"] = 0
                self._metrics[key] = entry
            if kind == "counter":
                entry["value"] += value
            elif kind == "gauge":
                entry["value"] = value
            else:
                idx = bisect.bisect_left(entry["buckets"], value)
                entry["counts"][idx] += 1
                entry["sum"] += value
                entry["count"] += 1
            self._ensure_flusher()

    def _ensure_flusher(self) -> None:
        if self._flusher is None or not self._flusher.is_alive():
            self._flusher = threading.Thread(
                target=self._flush_loop, name="metrics-flush", daemon=True)
            self._flusher.start()

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._metrics.values()]

    def _flush_loop(self) -> None:
        from ray_tpu.core import runtime

        while True:
            time.sleep(5.0)
            core = runtime._core_worker
            if core is None:
                continue
            try:
                core.controller.notify(
                    "push_metrics",
                    {"node_id": core.node_id.binary(),
                     "worker_id": core.worker_id.binary(),
                     "pid": __import__("os").getpid()},
                    self.snapshot())
            except Exception:
                from ray_tpu.util.ratelimit import log_every

                # Metrics are droppable, but a push that fails every
                # 5 s tick means the head is unreachable — worth a line.
                log_every("metrics.push", 60.0,
                          logging.getLogger(__name__),
                          "metrics push to controller failed",
                          exc_info=True)


class _Metric:
    kind = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return merged


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        _Registry.get().record(self._name, "counter", self._tags(tags),
                               value)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        _Registry.get().record(self._name, "gauge", self._tags(tags), value)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = tuple(boundaries or _DEFAULT_BUCKETS)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        _Registry.get().record(self._name, "histogram", self._tags(tags),
                               value, self._boundaries)


def prometheus_text(aggregated: Dict[str, Any]) -> str:
    """Render the controller's aggregated metrics as Prometheus exposition
    text (the shape the reference's node agent exposes)."""
    lines: List[str] = []
    for source, metrics in aggregated.items():
        for m in metrics:
            tags = dict(m.get("tags", {}))
            tags["source"] = source
            label = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
            if m["kind"] == "histogram":
                lines.append(f'{m["name"]}_sum{{{label}}} {m["sum"]}')
                lines.append(f'{m["name"]}_count{{{label}}} {m["count"]}')
            else:
                lines.append(f'{m["name"]}{{{label}}} {m["value"]}')
    return "\n".join(lines) + "\n"
