"""Metrics: Counter / Gauge / Histogram with cluster export.

Analogue of the reference's two-layer metrics pipeline: the user API
(``python/ray/util/metrics.py`` Counter/Gauge/Histogram) and the C++
registry exported to the node agent and on to Prometheus
(``src/ray/stats/metric_defs.cc:44-183``, ``metric_exporter.cc``).
Here: every process has a registry; a daemon flusher pushes snapshots to
the cluster controller (tagged with node/worker identity), which aggregates
them and serves them via the state API (``list_metrics``) and a
Prometheus-text endpoint (``metrics_text``).
"""

from __future__ import annotations

import bisect
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)


class _Registry:
    _instance: Optional["_Registry"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, tuple], Dict[str, Any]] = {}
        self._flusher: Optional[threading.Thread] = None

    @classmethod
    def get(cls) -> "_Registry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def record(self, name: str, kind: str, tags: Dict[str, str],
               value: float, buckets=None) -> None:
        self.record_many(name, kind, tags, (value,), buckets)

    def record_many(self, name: str, kind: str, tags: Dict[str, str],
                    values, buckets=None) -> None:
        """Bulk record under ONE lock acquisition — hot producers (the
        decode engine flushing a step's worth of observations) pay a
        single registry round-trip instead of one per value."""
        key = (name, tuple(sorted(tags.items())))
        with self._lock:
            entry = self._metrics.get(key)
            if entry is None:
                entry = {"name": name, "kind": kind, "tags": dict(tags),
                         "value": 0.0}
                if kind == "histogram":
                    entry["buckets"] = list(buckets or _DEFAULT_BUCKETS)
                    entry["counts"] = [0] * (len(entry["buckets"]) + 1)
                    entry["sum"] = 0.0
                    entry["count"] = 0
                self._metrics[key] = entry
            for value in values:
                if kind == "counter":
                    entry["value"] += value
                elif kind == "gauge":
                    entry["value"] = value
                else:
                    idx = bisect.bisect_left(entry["buckets"], value)
                    entry["counts"][idx] += 1
                    entry["sum"] += value
                    entry["count"] += 1
            self._ensure_flusher()

    def _ensure_flusher(self) -> None:
        if self._flusher is None or not self._flusher.is_alive():
            self._flusher = threading.Thread(
                target=self._flush_loop, name="metrics-flush", daemon=True)
            self._flusher.start()

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for e in self._metrics.values():
                d = dict(e)
                if "counts" in d:
                    # Deep-copy the mutable histogram state: the shallow
                    # dict still aliases the live counts list, and the
                    # flusher serializes this snapshot OUTSIDE the lock.
                    d["counts"] = list(d["counts"])
                    d["buckets"] = list(d["buckets"])
                out.append(d)
            return out

    def flush_now(self) -> bool:
        """Push one snapshot to the cluster controller synchronously
        (tests and benches that cannot wait out the flush interval).
        Returns False when no runtime is connected or the push failed."""
        from ray_tpu.core import runtime

        core = runtime._core_worker
        if core is None:
            return False
        try:
            core.controller.notify("push_metrics", self._source(core),
                                   self.snapshot())
            return True
        except Exception:
            return False

    @staticmethod
    def _source(core) -> Dict[str, Any]:
        return {"node_id": core.node_id.binary(),
                "worker_id": core.worker_id.binary(),
                "pid": __import__("os").getpid()}

    def _flush_loop(self) -> None:
        from ray_tpu.core import runtime
        from ray_tpu.core.config import config as rt_config

        while True:
            time.sleep(max(0.1, rt_config.metrics_flush_interval_s))
            core = runtime._core_worker
            if core is None:
                continue
            try:
                core.controller.notify("push_metrics", self._source(core),
                                       self.snapshot())
            except Exception:
                from ray_tpu.util.ratelimit import log_every

                # Metrics are droppable, but a push that fails every
                # 5 s tick means the head is unreachable — worth a line.
                log_every("metrics.push", 60.0,
                          logging.getLogger(__name__),
                          "metrics push to controller failed",
                          exc_info=True)


class _Metric:
    kind = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return merged


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        _Registry.get().record(self._name, "counter", self._tags(tags),
                               value)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        _Registry.get().record(self._name, "gauge", self._tags(tags), value)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = tuple(boundaries or _DEFAULT_BUCKETS)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        _Registry.get().record(self._name, "histogram", self._tags(tags),
                               value, self._boundaries)

    def observe_many(self, values: Sequence[float],
                     tags: Optional[Dict[str, str]] = None) -> None:
        """Record a batch of observations under one registry lock."""
        if values:
            _Registry.get().record_many(self._name, "histogram",
                                        self._tags(tags), values,
                                        self._boundaries)


def prometheus_text(aggregated: Dict[str, Any]) -> str:
    """Render the controller's aggregated metrics as Prometheus exposition
    text (the shape the reference's node agent exposes). Histograms emit
    the full cumulative ``_bucket{le=...}`` ladder (+Inf last) so a real
    Prometheus can compute quantiles with histogram_quantile()."""
    lines: List[str] = []
    for source, metrics in aggregated.items():
        for m in metrics:
            tags = dict(m.get("tags", {}))
            tags["source"] = source
            label = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
            if m["kind"] == "histogram":
                cum = 0
                for edge, n in zip(m["buckets"], m["counts"]):
                    cum += n
                    blabel = (label + "," if label else "") + f'le="{edge}"'
                    lines.append(f'{m["name"]}_bucket{{{blabel}}} {cum}')
                blabel = (label + "," if label else "") + 'le="+Inf"'
                lines.append(f'{m["name"]}_bucket{{{blabel}}} {m["count"]}')
                lines.append(f'{m["name"]}_sum{{{label}}} {m["sum"]}')
                lines.append(f'{m["name"]}_count{{{label}}} {m["count"]}')
            else:
                lines.append(f'{m["name"]}{{{label}}} {m["value"]}')
    return "\n".join(lines) + "\n"


# ------------------------------------------------ aggregation helpers
#
# Shared by serve.status()'s SLO summaries, the dashboard's serve panel
# and the benches: ONE way to merge per-process histogram snapshots and
# read quantiles out of them, so every surface reports the same number.


def merge_histograms(aggregated: Dict[str, List[Dict[str, Any]]],
                     name: str) -> Dict[tuple, Dict[str, Any]]:
    """Merge same-name histogram entries across sources, keyed by their
    tag items. Entries whose bucket boundaries disagree are skipped (the
    metrics-name-collision lint makes that a build failure)."""
    out: Dict[tuple, Dict[str, Any]] = {}
    for metrics in aggregated.values():
        for m in metrics:
            if m.get("name") != name or m.get("kind") != "histogram":
                continue
            key = tuple(sorted(m.get("tags", {}).items()))
            cur = out.get(key)
            if cur is None:
                out[key] = {"name": name, "kind": "histogram",
                            "tags": dict(m.get("tags", {})),
                            "buckets": list(m["buckets"]),
                            "counts": list(m["counts"]),
                            "sum": m["sum"], "count": m["count"]}
            elif cur["buckets"] == list(m["buckets"]):
                cur["counts"] = [a + b for a, b in zip(cur["counts"],
                                                       m["counts"])]
                cur["sum"] += m["sum"]
                cur["count"] += m["count"]
    return out


def histogram_quantile(entry: Dict[str, Any], q: float) -> Optional[float]:
    """Bucket-interpolated quantile of one (merged) histogram entry —
    Prometheus histogram_quantile() semantics: linear within the bucket,
    the top (+Inf) bucket clamps to its lower edge. None when empty.
    Quantiles are bucket-QUANTIZED: precision is the bucket grid, which
    is the documented trade for surviving process death and transport."""
    total = entry.get("count", 0)
    if not total:
        return None
    rank = q * total
    cum = 0
    prev_edge = 0.0
    for edge, n in zip(entry["buckets"], entry["counts"]):
        if cum + n >= rank and n > 0:
            frac = (rank - cum) / n
            return prev_edge + (edge - prev_edge) * max(0.0, min(1.0, frac))
        cum += n
        prev_edge = edge
    return prev_edge  # landed in the +Inf bucket: clamp to the last edge


def histogram_summary(entry: Dict[str, Any]) -> Dict[str, Any]:
    """{count, mean, p50, p99} of one merged histogram entry."""
    count = entry.get("count", 0)
    return {
        "count": count,
        "mean": (entry["sum"] / count) if count else None,
        "p50": histogram_quantile(entry, 0.5),
        "p99": histogram_quantile(entry, 0.99),
    }


def counter_totals(aggregated: Dict[str, List[Dict[str, Any]]],
                   name: str) -> Dict[tuple, float]:
    """Sum same-name counter entries across sources, keyed by tag items."""
    out: Dict[tuple, float] = {}
    for metrics in aggregated.values():
        for m in metrics:
            if m.get("name") == name and m.get("kind") == "counter":
                key = tuple(sorted(m.get("tags", {}).items()))
                out[key] = out.get(key, 0.0) + m.get("value", 0.0)
    return out
