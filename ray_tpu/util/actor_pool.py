"""ActorPool: work distribution over a fixed set of actors.

Analogue of the reference's ``ray.util.ActorPool``
(``python/ray/util/actor_pool.py``): submit tasks to idle actors, collect
results in order or as-available.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._future_to_index = {}  # O(1) unordered pops (no ref scan)
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def submit(self, fn: Callable, value: Any) -> None:
        if not self._idle:
            raise ValueError("no idle actors; call get_next first")
        actor = self._idle.pop(0)
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor
        self._future_to_index[ref] = self._next_task_index
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def get_next(self, timeout=None) -> Any:
        """Next result in submission order (skipping indices already
        consumed by ``get_next_unordered``)."""
        while (self._next_return_index < self._next_task_index
               and self._next_return_index not in self._index_to_future):
            self._next_return_index += 1
        ref = self._index_to_future.pop(self._next_return_index)
        self._future_to_index.pop(ref, None)
        self._next_return_index += 1
        value = ray_tpu.get(ref, timeout=timeout)
        self._idle.append(self._future_to_actor.pop(ref))
        return value

    def get_next_unordered(self, timeout=None) -> Any:
        pending = list(self._future_to_actor.keys())
        ready, _ = ray_tpu.wait(pending, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result ready")
        ref = ready[0]
        idx = self._future_to_index.pop(ref)  # O(1): ref -> index map
        self._index_to_future.pop(idx, None)
        value = ray_tpu.get(ref)
        self._idle.append(self._future_to_actor.pop(ref))
        return value

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            while not self._idle:
                yield self.get_next()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def has_free(self) -> bool:
        return bool(self._idle)
