"""Deterministic, config-gated fault injection for chaos tests.

PR 3's chaos test reached for a raw ``os.kill(pid, SIGKILL)`` — fine for
one test, but every new fault scenario (kill the controller mid-deploy,
fail exactly the third ``release_subslice`` RPC, pause a node's
heartbeats, partition one peer) re-invents its own ad-hoc monkeypatching
that only works inside the test's own process. This module is the shared
harness: product code declares named INJECTION POINTS with
:func:`check`, and tests activate RULES against them — across process
boundaries — via a JSON rules file.

Design constraints, in order:

* **Zero cost when off.** ``check(site)`` is one config-attribute read
  when ``config.faultinject_path`` is empty (the default). No stat, no
  allocation: product hot paths gate the f-string building the site name
  behind the same flag.
* **Cross-process.** The serve controller, replicas and proxies are
  actor WORKER processes; a registry in the test process can't reach
  them. Rules live in a file (``config.faultinject_path``, set through
  the ``RAY_TPU_FAULTINJECT_PATH`` env var *before* ``ray_tpu.init`` so
  every spawned worker inherits it) and are re-read on mtime change, so
  a test can install/remove rules while workers run.
* **Deterministic.** Rules fire on the Nth matching pass (``after``
  skips, ``times`` caps, both counted per process), not on wall-clock
  raciness. ``once_global: true`` adds a cross-process fuse (an
  ``O_EXCL`` marker file next to the rules file) so "SIGKILL the
  controller once" can't become a kill loop when the restarted process
  reaches the same site.

Rule shape (one JSON object per rule, in a top-level list)::

    {"site": "serve.controller.reconcile_tick",  # fnmatch glob
     "action": "die",          # die | error | delay | drop
     "after": 0,               # skip the first N matches (per process)
     "times": -1,              # fire at most N times (-1 = unlimited)
     "once_global": true,      # cross-process single fire (marker file)
     "delay_s": 0.5,           # delay action only
     "id": "kill-ctl"}         # optional; defaults to site+action

Actions:

* ``die`` — ``SIGKILL`` the calling process at the site (no cleanup, no
  atexit: the honest crash).
* ``error`` — raise :class:`FaultInjected` (a ``RuntimeError``): the
  typed "this RPC/endpoint failed" signal. Deliberately NOT an
  ``OSError`` so ``ReconnectingClient`` surfaces it immediately instead
  of burning its retry window.
* ``delay`` — ``time.sleep(delay_s)``: pause heartbeats, stall a
  handler, stretch a restart into a measurable outage window.
* ``drop`` — raise :class:`FaultDropped`. At the RPC client it behaves
  like a torn connection (it subclasses ``ConnectionError``, so
  reconnect/retry paths engage — that's a network partition); inside
  ``RpcServer._handle`` it is caught and the reply is silently never
  sent (the caller's timeout governs — that's a lost reply).

Sites instrumented in-tree: ``rpc.server.<server>.<method>``,
``rpc.client.<method>``, ``rpc.dial.<host>:<port>``,
``node.heartbeat``, the serve controller lifecycle points
(``serve.controller.init`` / ``.restore`` / ``.save_state`` /
``.reconcile_tick`` / ``.retry_pending_releases`` / ``.deploy``), and
the multihost gang (``multihost.barrier.<group>.<member>`` at member-
side barrier entry — a delay/drop rule manufactures a straggler for
the doctor's gang-hang signature — and
``multihost.member.<group>.<member>.beat`` in the member heartbeat
loop, where a ``die`` rule SIGKILLs exactly that host's worker).
"""

from __future__ import annotations

import fnmatch
import json
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["FaultInjected", "FaultDropped", "check", "Faults",
           "reset_counters"]


class FaultInjected(RuntimeError):
    """An injected endpoint/operation failure (typed, non-transport)."""


class FaultDropped(ConnectionError):
    """An injected drop: torn connection client-side, eaten reply
    server-side (``RpcServer._handle`` catches it and never replies)."""


_lock = threading.Lock()
# Rules cache keyed by (path, mtime_ns, size): a test rewriting the file
# is picked up on the next check without a per-check parse.
_cache: Dict[str, Any] = {"path": None, "stamp": None, "rules": []}
# Per-process match counters per rule id (determinism: "the Nth pass").
_counts: Dict[str, int] = {}


def _rule_id(rule: Dict[str, Any]) -> str:
    return str(rule.get("id") or
               f"{rule.get('site', '')}#{rule.get('action', 'error')}")


def _load(path: str) -> List[Dict[str, Any]]:
    try:
        st = os.stat(path)
    except OSError:
        return []
    stamp = (st.st_mtime_ns, st.st_size)
    with _lock:
        if _cache["path"] == path and _cache["stamp"] == stamp:
            return _cache["rules"]
    try:
        with open(path) as f:
            rules = json.load(f)
        if not isinstance(rules, list):
            rules = []
    except (OSError, ValueError):
        # Mid-rewrite read (the writer uses os.replace, but a foreign
        # writer might not): treat as "no rules this pass", the next
        # stat sees the settled file.
        return []
    with _lock:
        _cache.update(path=path, stamp=stamp, rules=rules)
    return rules


def check(site: str) -> None:
    """Product-code injection point. No-op unless a rules file is
    configured AND a rule matches ``site``; see the module docstring
    for rule semantics. May raise :class:`FaultInjected` /
    :class:`FaultDropped`, sleep, or SIGKILL the process."""
    from ray_tpu.core.config import config

    path = config.faultinject_path
    if not path:
        return
    for rule in _load(path):
        if not fnmatch.fnmatchcase(site, str(rule.get("site", ""))):
            continue
        rid = _rule_id(rule)
        with _lock:
            n = _counts.get(rid, 0) + 1
            _counts[rid] = n
        after = int(rule.get("after", 0))
        if n <= after:
            continue
        times = int(rule.get("times", -1))
        if times >= 0 and n > after + times:
            continue
        if rule.get("once_global"):
            marker = f"{path}.{rid}.fired"
            try:
                os.close(os.open(marker,
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY))
            except FileExistsError:
                continue  # already fired in some process: fuse blown
            except OSError:
                continue  # marker dir unwritable: fail safe (don't fire)
        _fire(rule, site)


def _fire(rule: Dict[str, Any], site: str) -> None:
    action = rule.get("action", "error")
    from ray_tpu.util import flightrec

    # The flight recorder is the one witness an injected crash leaves
    # behind: record the fire, and for `die` flush synchronously — the
    # SIGKILL gives the background flusher no chance.
    flightrec.record("fault.fired", site=site, action=action)
    if action == "die":
        # SIGKILL self: no cleanup, no atexit, no further flush — the
        # honest crash the control plane must tolerate (the recorder
        # file written above is evidence, not cleanup: the process
        # state it describes still evaporates).
        flightrec.flush_now()
        os.kill(os.getpid(), signal.SIGKILL)
    elif action == "delay":
        time.sleep(float(rule.get("delay_s", 0.1)))
    elif action == "drop":
        raise FaultDropped(f"faultinject: dropped at {site}")
    else:
        raise FaultInjected(f"faultinject: injected failure at {site}")


def reset_counters() -> None:
    """Forget this process's per-rule match counters (test isolation)."""
    with _lock:
        _counts.clear()


class Faults:
    """Test-side owner of a rules file.

    ::

        with Faults(path) as f:
            f.add("rpc.client.release_subslice", "error")
            kill = f.add("serve.controller.reconcile_tick", "die",
                         once_global=True)
            ...
            f.remove(kill)      # live update: workers re-read on mtime

    ``path`` must equal ``config.faultinject_path`` in every process
    under test — set ``RAY_TPU_FAULTINJECT_PATH`` before
    ``ray_tpu.init`` (workers inherit the environment) and the config
    flag in the test process. Exit clears the file and any
    ``once_global`` marker files."""

    def __init__(self, path: str):
        self.path = path
        self._rules: List[Dict[str, Any]] = []

    # ------------------------------------------------------------ rules

    def add(self, site: str, action: str = "error",
            after: int = 0, times: int = -1, once_global: bool = False,
            delay_s: float = 0.1, rule_id: Optional[str] = None
            ) -> Dict[str, Any]:
        rule = {"site": site, "action": action, "after": after,
                "times": times, "once_global": once_global,
                "delay_s": delay_s}
        if rule_id:
            rule["id"] = rule_id
        self._rules.append(rule)
        self._write()
        return rule

    def remove(self, rule: Dict[str, Any]) -> None:
        self._rules = [r for r in self._rules if r is not rule]
        self._write()

    def clear(self) -> None:
        self._rules = []
        self._write()

    def _write(self) -> None:
        # Atomic replace: a worker's _load never sees a torn file.
        tmp = f"{self.path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self._rules, f)
        os.replace(tmp, self.path)

    def marker_fired(self, rule: Dict[str, Any]) -> bool:
        """Whether a ``once_global`` rule's cross-process fuse blew —
        i.e. some process reached the site and fired the action."""
        return os.path.exists(f"{self.path}.{_rule_id(rule)}.fired")

    # ------------------------------------------------------- lifecycle

    def __enter__(self) -> "Faults":
        self._write()
        return self

    def __exit__(self, *exc) -> None:
        for rule in list(self._rules):
            try:
                os.unlink(f"{self.path}.{_rule_id(rule)}.fired")
            except OSError:
                pass
        self._rules = []
        try:
            self._write()
            os.unlink(self.path)
        except OSError:
            pass
