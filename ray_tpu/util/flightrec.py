"""Cluster flight recorder: a bounded, always-on ring of structured
events per process, persisted for post-mortem forensics.

The metrics pipeline (``core/coremetrics.py``) answers "how much / how
fast"; the tracing spans (``util/tracing.py``) answer "where did this
request's time go". Neither survives the interesting failure: a
SIGKILLed stage actor takes its gauges with it, and the doctor is left
inferring a gang death from metric *deltas*. This module records the
few dozen discrete control-plane facts that explain a crash — gang
epochs and reconciles, barrier entries, pipeline stage clocks,
snapshot pushes/pulls, fault-injection fires, actor death causes — in
a ring cheap enough to never turn off, and makes them outlive the
process that recorded them.

Design constraints, in order:

* **Cheap enough to be always-on.** :func:`record` is one config
  attribute read plus a ``deque.append`` (atomic under the GIL — no
  lock is taken that the caller did not already hold). Event dicts are
  built by the caller only after the enabled check; sites on hot paths
  gate their f-strings the same way the faultinject sites do.
  ``make bench-obs`` pins the recorder-on-vs-off delta on the pipeline
  step loop (<2% bar).
* **Survives the process.** A daemon flusher writes the ring to
  ``<flightrec_dir>/fr-<pid>.json`` (atomic replace) every
  ``flightrec_flush_s`` while events keep arriving, plus an ``atexit``
  final flush for orderly deaths. A SIGKILL keeps everything up to the
  last flush — and the one SIGKILL source this repo aims at itself
  (``util/faultinject.py`` ``die`` rules) flushes synchronously right
  before the kill, so an injected crash is fully recorded.
* **Merged after the fact.** :func:`dump_all` reads every per-process
  file back into ``{source: {"pid", "role", "events"}}``;
  ``ray_tpu doctor --post-mortem`` (``doctor.post_mortem``) merges the
  sources by wall-clock and explains the death from evidence. The
  controller exposes the same merge as the ``fr_dump`` RPC. The dir is
  per-HOST: on a real multi-host rig, collect each host's
  ``flightrec_dir`` (the post-mortem takes any merged dict).

Event shape: ``{"ev": <name>, "ts": <wall-clock>, **attrs}`` with flat,
JSON-safe attrs. Event names are literal at every call site and go
through the same graftlint family-#10 checks as metric names (one name,
one attr schema; id-shaped attr VALUES flagged — bounded schedule ints
like ``step``/``mb``/``stage`` are exempt). The in-tree catalog lives
in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["record", "dump", "dump_all", "cluster_dump", "flush_now",
           "reset"]

# The ring itself: created lazily on first record so importing this
# module costs nothing. deque.append is the only hot-path operation.
_ring: Optional[deque] = None
# Flusher bookkeeping (slow path only).
_lock = threading.Lock()
_flusher_started = False
_written = 0          # events appended since the last flush (approx)


def record(ev: str, **attrs: Any) -> None:
    """Append one event to this process's ring. One attribute read when
    the recorder is off; a plain deque append when on. Never raises."""
    from ray_tpu.core.config import config

    if not config.flightrec_enabled:
        return
    global _ring, _written
    ring = _ring
    if ring is None:
        with _lock:
            if _ring is None:
                _ring = deque(maxlen=max(16, int(config.flightrec_ring)))
            ring = _ring
        _ensure_flusher()
    event = {"ev": ev, "ts": time.time()}
    event.update(attrs)
    ring.append(event)
    _written += 1


def audit(ev: str, **attrs: Any) -> Optional[str]:
    """Durable append: :func:`record` + an immediate :func:`flush_now`.
    For events that must survive the process dying right after the
    decision they capture (the autopilot's control actions) — the
    normal ring only reaches disk on the background flush cadence.
    Returns the flushed path (None when the recorder is off or the dir
    is unwritable); like ``record``, never raises."""
    record(ev, **attrs)
    return flush_now()


def dump() -> List[Dict[str, Any]]:
    """This process's events, oldest first."""
    ring = _ring
    return list(ring) if ring is not None else []


def reset() -> None:
    """Drop this process's ring and its persisted file (test isolation)."""
    global _ring, _written
    with _lock:
        _ring = None
        _written = 0
    try:
        os.unlink(_path())
    except OSError:
        pass


# ------------------------------------------------------------ persistence


def _dir() -> str:
    from ray_tpu.core.config import config

    return config.flightrec_dir


def _path() -> str:
    return os.path.join(_dir(), f"fr-{os.getpid()}.json")


def _role() -> str:
    try:
        from ray_tpu.core import runtime

        core = runtime._core_worker
        if core is not None:
            return getattr(core, "mode", "worker")
    except Exception:  # graftlint: disable=swallowed-exception (role is cosmetic; the recorder must never take a process down)
        pass
    return "proc"


def flush_now() -> Optional[str]:
    """Write the ring to this process's recorder file (atomic replace).
    Returns the path, or None when there is nothing to write or the dir
    is unwritable (the recorder must never take a process down)."""
    global _written
    ring = _ring
    if ring is None:
        return None
    events = list(ring)
    path = _path()
    try:
        os.makedirs(_dir(), exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"pid": os.getpid(), "role": _role(),
                       "flushed_at": time.time(), "events": events}, f,
                      default=str)
        os.replace(tmp, path)
    except OSError:
        return None
    _written = 0
    return path


def _ensure_flusher() -> None:
    global _flusher_started
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True
    t = threading.Thread(target=_flush_loop, name="flightrec-flush",
                         daemon=True)
    t.start()
    atexit.register(flush_now)


def _flush_loop() -> None:
    from ray_tpu.core.config import config

    while True:
        time.sleep(max(0.05, config.flightrec_flush_s))
        if _written:
            flush_now()


# ------------------------------------------------------------ collection


def dump_all(fr_dir: Optional[str] = None,
             max_age_s: Optional[float] = None) -> Dict[str, Any]:
    """Read every persisted recorder file under ``fr_dir`` (default:
    the configured ``flightrec_dir``) back into
    ``{source: {"pid", "role", "events"}}`` — the post-mortem's input.
    Unreadable/torn files are skipped (a crash mid-replace leaves the
    previous complete file). ``max_age_s`` drops files whose last flush
    is older (stale pids from a previous session on a shared dir)."""
    fr_dir = fr_dir or _dir()
    out: Dict[str, Any] = {}
    try:
        names = sorted(os.listdir(fr_dir))
    except OSError:
        return out
    now = time.time()
    for name in names:
        if not (name.startswith("fr-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(fr_dir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict) or "events" not in doc:
            continue
        if (max_age_s is not None
                and now - float(doc.get("flushed_at", 0)) > max_age_s):
            continue
        source = f"{doc.get('role', 'proc')}-pid{doc.get('pid', '?')}"
        out[source] = {"pid": doc.get("pid"), "role": doc.get("role"),
                       "events": list(doc.get("events") or [])}
    return out


def cluster_dump() -> Dict[str, Any]:
    """Flush this process's ring, then merge every recorder file on
    this host — the ``fr_dump`` controller RPC body. (Per-host: on a
    real rig, run it on each host or collect the dirs.)"""
    flush_now()
    return dump_all()
