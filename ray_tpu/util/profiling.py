"""On-demand worker profiling: CPU flamegraphs + heap snapshots.

Analogue of the reference's dashboard profiling endpoints
(``dashboard/modules/reporter/profile_manager.py:79`` attaches py-spy for
CPU flamegraphs, ``:190`` memray for heap). Here both are NATIVE and
zero-dependency: a sampling thread collapses ``sys._current_frames`` into
folded stacks (the flamegraph input format), rendered as a self-contained
SVG; heap profiling uses ``tracemalloc`` snapshots with growth diffing
between calls. Exposed as RPCs on every live worker (``profile_cpu`` /
``profile_heap``), surfaced through the ``ray_tpu profile`` CLI and the
dashboard's per-worker drill-down pages.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

# ------------------------------------------------------------ CPU sampling


def sample_stacks(duration_s: float = 3.0, hz: float = 100.0,
                  exclude_self: bool = True) -> Dict[str, int]:
    """Sample every thread's Python stack for ``duration_s`` and return
    folded stacks ("frame;frame;frame" -> sample count) — the flamegraph
    wire format. Pure-Python sampling costs one GIL hop per tick; at
    100 Hz that is well under 1% overhead."""
    counts: Dict[str, int] = {}
    me = threading.get_ident()
    period = 1.0 / max(1.0, hz)
    deadline = time.monotonic() + duration_s
    while time.monotonic() < deadline:
        for tid, top in sys._current_frames().items():
            if exclude_self and tid == me:
                continue
            frames: List[str] = []
            frame = top
            while frame is not None:
                code = frame.f_code
                frames.append(
                    f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}"
                    f":{frame.f_lineno})")
                frame = frame.f_back
            key = ";".join(reversed(frames))
            counts[key] = counts.get(key, 0) + 1
        time.sleep(period)
    return counts


# ------------------------------------------------------------- flamegraph


class _Node:
    __slots__ = ("name", "value", "children")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self.children: Dict[str, "_Node"] = {}


def _build_trie(folded: Dict[str, int]) -> _Node:
    root = _Node("all")
    for stack, count in folded.items():
        root.value += count
        node = root
        for frame in stack.split(";"):
            child = node.children.get(frame)
            if child is None:
                child = _Node(frame)
                node.children[frame] = child
            child.value += count
            node = child
    return root


def _color(name: str) -> str:
    h = hash(name) & 0xFFFF
    r = 205 + (h % 50)
    g = 80 + ((h >> 4) % 110)
    b = 40 + ((h >> 8) % 40)
    return f"rgb({r},{g},{b})"


def flamegraph_svg(folded: Dict[str, int], width: int = 1100,
                   row_h: int = 17, title: str = "CPU flamegraph") -> str:
    """Render folded stacks as a self-contained SVG flamegraph (hover
    titles carry frame + sample counts; no JS, no external assets)."""
    root = _build_trie(folded)
    if root.value == 0:
        # Keep the caller's title: it often carries the ERROR ("no worker
        # xyz") and a bare "no samples" would read as an idle process.
        safe = (title.replace("&", "&amp;").replace("<", "&lt;"))
        return ("<svg xmlns='http://www.w3.org/2000/svg' width='700' "
                f"height='40'><text x='5' y='25'>{safe} — no samples"
                "</text></svg>")

    def depth(node: _Node) -> int:
        return 1 + max((depth(c) for c in node.children.values()),
                       default=0)

    def esc(s: str) -> str:
        return (s.replace("&", "&amp;").replace("<", "&lt;")
                .replace(">", "&gt;").replace("'", "&apos;"))

    height = (depth(root) + 2) * row_h
    out = [
        f"<svg xmlns='http://www.w3.org/2000/svg' width='{width}' "
        f"height='{height}' font-family='monospace' font-size='11'>",
        f"<text x='5' y='{row_h - 4}' font-size='13'>{esc(title)} "
        f"({root.value} samples)</text>",
    ]

    def emit(node: _Node, x: float, y: int, w: float) -> None:
        if w < 1.0:
            return
        pct = 100.0 * node.value / root.value
        out.append(
            f"<g><title>{esc(node.name)} — {node.value} samples "
            f"({pct:.1f}%)</title>"
            f"<rect x='{x:.1f}' y='{y}' width='{w:.1f}' "
            f"height='{row_h - 1}' fill='{_color(node.name)}' rx='1'/>")
        if w > 40:
            label = esc(node.name)[:int(w / 6.5)]
            out.append(f"<text x='{x + 2:.1f}' y='{y + row_h - 5}' "
                       f"fill='#222'>{label}</text>")
        out.append("</g>")
        cx = x
        for child in sorted(node.children.values(),
                            key=lambda c: -c.value):
            cw = w * child.value / node.value
            emit(child, cx, y + row_h, cw)
            cx += cw

    emit(root, 0.0, row_h, float(width))
    out.append("</svg>")
    return "".join(out)


# ------------------------------------------------------------------ heap

_heap_lock = threading.Lock()
_heap_prev = None  # previous snapshot for growth diffing


def stop_heap_profile() -> Dict[str, object]:
    """Turn allocation tracing back OFF (tracing costs every allocation a
    traceback capture — a diagnostic probe must not slow the worker for
    the rest of its life)."""
    import tracemalloc

    global _heap_prev
    with _heap_lock:
        was = tracemalloc.is_tracing()
        if was:
            tracemalloc.stop()
        _heap_prev = None
        return {"stopped": was}


def heap_profile(top_n: int = 25) -> Dict[str, object]:
    """tracemalloc snapshot of this process. First call starts tracing
    (subsequent allocations get tracked); later calls return the top
    allocation sites AND the growth since the previous call (the memray
    'leaks between two points' workflow). Call :func:`stop_heap_profile`
    (RPC ``profile_heap_stop``) when done."""
    import tracemalloc

    global _heap_prev
    with _heap_lock:
        if not tracemalloc.is_tracing():
            tracemalloc.start(16)
            _heap_prev = None
            return {"started": True,
                    "note": "tracing started; call again to see "
                            "allocations made from now on, and "
                            "profile_heap_stop when done"}
        snap = tracemalloc.take_snapshot()
        snap = snap.filter_traces([
            tracemalloc.Filter(False, tracemalloc.__file__),
            tracemalloc.Filter(False, "<frozen importlib._bootstrap>"),
        ])
        top = [{
            "site": str(stat.traceback[-1]) if stat.traceback else "?",
            "size_kb": round(stat.size / 1024, 1),
            "count": stat.count,
        } for stat in snap.statistics("lineno")[:top_n]]
        growth = []
        if _heap_prev is not None:
            growth = [{
                "site": str(stat.traceback[-1]) if stat.traceback else "?",
                "size_diff_kb": round(stat.size_diff / 1024, 1),
                "count_diff": stat.count_diff,
            } for stat in snap.compare_to(_heap_prev, "lineno")[:top_n]]
        _heap_prev = snap
        current, peak = tracemalloc.get_traced_memory()
        return {"started": False,
                "traced_current_kb": round(current / 1024, 1),
                "traced_peak_kb": round(peak / 1024, 1),
                "top": top, "growth_since_last": growth}


def list_cluster_workers(controller_client, prefix: Optional[str] = None,
                         rpc_timeout: float = 10.0) -> List[Dict]:
    """Enumerate live workers across all alive nodes (each row carries a
    ``node_id``). One bounded RPC per node; unreachable nodes are skipped
    and never leak a client. Shared by the CLI and the dashboard."""
    from ray_tpu.core.rpc import RpcClient

    out: List[Dict] = []
    for node in controller_client.call("list_nodes",
                                       timeout=rpc_timeout):
        if not node.get("alive"):
            continue
        node_client = None
        try:
            node_client = RpcClient(tuple(node["addr"]))
            workers = node_client.call("list_workers",
                                       timeout=rpc_timeout)
        except Exception:
            continue
        finally:
            if node_client is not None:
                node_client.close()
        for w in workers:
            if prefix is None or w["worker_id"].startswith(prefix):
                w["node_id"] = node["node_id"]
                out.append(w)
    return out


def profile_worker(addr: Tuple[str, int], duration_s: float = 3.0,
                   hz: float = 100.0,
                   timeout: Optional[float] = None) -> Dict[str, int]:
    """Client helper: folded stacks from a live worker's profile_cpu RPC."""
    from ray_tpu.core.rpc import RpcClient

    client = RpcClient(tuple(addr))
    try:
        return client.call("profile_cpu", duration_s, hz,
                           timeout=timeout or duration_s + 30.0)
    finally:
        client.close()
