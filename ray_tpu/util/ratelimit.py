"""Rate-limited logging for hot paths and reconcile loops.

The PR 2 ``DecodeEngine._emit`` pattern, factored out: a failure that
can repeat thousands of times per second (every heartbeat, every
reconcile tick, every streamed token) must be *diagnosable* without
drowning the log. ``log_every`` emits at most one record per key per
period and counts what it suppressed, so the first line after a quiet
stretch says how many identical failures it stands for.

Used by the swallowed-exception fixes graftlint drove (see
docs/ANALYSIS.md): ``except Exception: pass`` on a request/daemon path
becomes ``except Exception: log_every(...)``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Tuple

_lock = threading.Lock()
# key -> (last emit monotonic, suppressed count since)
_state: Dict[str, Tuple[float, int]] = {}


def log_every(key: str, period_s: float, logger: logging.Logger,
              msg: str, *args, level: int = logging.WARNING,
              exc_info: bool = False) -> bool:
    """Log ``msg % args`` at most once per ``period_s`` per ``key``.

    Returns True when the record was emitted. Suppressed repeats are
    counted and reported in the next emitted record's suffix.
    """
    now = time.monotonic()
    with _lock:
        last, suppressed = _state.get(key, (0.0, 0))
        emit = now - last >= period_s
        if emit:
            _state[key] = (now, 0)
        else:
            _state[key] = (last, suppressed + 1)
    if not emit:
        _count_suppressed(key)  # outside _lock: registry has its own
        return False
    suffix = f" ({suppressed} similar suppressed)" if suppressed else ""
    try:
        logger.log(level, msg + suffix, *args, exc_info=exc_info)
    except Exception:
        # Logging must never take down the caller (interpreter teardown
        # closes handlers mid-write).
        return False
    return True


def _count_suppressed(key: str) -> None:
    """Every suppressed occurrence increments ``log_suppressed_total``
    labeled by its site key — a suppressed error FLOOD is invisible in
    the log by design, so it must be visible in the metrics pipeline
    instead (the counter growing while the log is quiet is the tell).
    Site keys are literal strings at the log_every call sites, so the
    label stays bounded."""
    try:
        from ray_tpu.util.metrics import Counter

        global _SUPPRESSED
        if _SUPPRESSED is None:
            _SUPPRESSED = Counter(
                "log_suppressed_total",
                "log_every records suppressed by rate limiting, by site.",
                tag_keys=("site",))
        _SUPPRESSED.inc(1.0, {"site": key})
    # The one place that CANNOT log its failure: log_every is the
    # logging path, and recursing into it from its own metrics hook
    # (or at interpreter teardown) must never take down the caller.
    # graftlint: disable=swallowed-exception
    except Exception:
        pass


_SUPPRESSED = None


def reset() -> None:
    """Test hook: forget all rate-limit state."""
    with _lock:
        _state.clear()
