"""Library-level collectives over actor groups — host-data plane.

Analogue of the reference's ``ray.util.collective``
(``util/collective/collective.py:120-615``: NCCL/Gloo groups over actors,
rendezvous via a named actor store). On the TPU stack this API deliberately
covers only *host* (numpy) data: device-tensor collectives are compiled XLA
collectives over the mesh (``ray_tpu.parallel``) — there is no NCCL-style
runtime plane to manage (SURVEY §5.8: "the mesh is declared, not
connected"). What remains useful at the framework level is CPU-side
coordination: allreduce/broadcast/allgather of numpy arrays between actors
(metrics fan-in, weight broadcast to env runners, rendezvous barriers),
implemented over the object store with a named rendezvous actor.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


class _GroupStore:
    """Rendezvous + per-round mailbox (reference: NCCLUniqueIDStore named
    actor used for rendezvous)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._rounds: Dict[tuple, Dict[int, Any]] = {}
        self._reads: Dict[tuple, set] = {}

    def put(self, op: str, round_id: int, rank: int, value) -> None:
        self._rounds.setdefault((op, round_id), {})[rank] = value

    def gather(self, op: str, round_id: int, rank: int):
        key = (op, round_id)
        entries = self._rounds.get(key, {})
        if len(entries) < self.world_size:
            return None
        result = [entries[r] for r in range(self.world_size)]
        # Only clear a round once every rank has read it — a rank-0-side
        # clear.remote() raced slower ranks' polls and made them time out.
        reads = self._reads.setdefault(key, set())
        reads.add(rank)
        if len(reads) == self.world_size:
            self._rounds.pop(key, None)
            self._reads.pop(key, None)
        return result


class CollectiveGroup:
    """Handle held by each participant (rank)."""

    def __init__(self, name: str, world_size: int, rank: int):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self._round: Dict[str, int] = {}
        self._store = ray_tpu.get_actor(f"_collective_{name}")

    def _next_round(self, op: str) -> int:
        r = self._round.get(op, 0)
        self._round[op] = r + 1
        return r

    def _exchange(self, op: str, value, timeout: float = 120.0):
        round_id = self._next_round(op)
        ray_tpu.get(self._store.put.remote(op, round_id, self.rank, value))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            gathered = ray_tpu.get(
                self._store.gather.remote(op, round_id, self.rank))
            if gathered is not None:
                return gathered
            time.sleep(0.005)
        raise TimeoutError(f"collective {op} round {round_id} timed out")

    # ------------------------------------------------------------ ops

    def allreduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        gathered = self._exchange("allreduce", np.asarray(array))
        stacked = np.stack(gathered)
        if op == "sum":
            return stacked.sum(axis=0)
        if op == "mean":
            return stacked.mean(axis=0)
        if op == "max":
            return stacked.max(axis=0)
        if op == "min":
            return stacked.min(axis=0)
        raise ValueError(f"unknown reduce op {op!r}")

    def allgather(self, array: np.ndarray) -> List[np.ndarray]:
        return self._exchange("allgather", np.asarray(array))

    def broadcast(self, array: Optional[np.ndarray],
                  src_rank: int = 0) -> np.ndarray:
        gathered = self._exchange(
            "broadcast", np.asarray(array) if self.rank == src_rank else None)
        return gathered[src_rank]

    def barrier(self) -> None:
        self._exchange("barrier", self.rank)


def create_collective_group(name: str, world_size: int) -> None:
    """Create the rendezvous store (call once, e.g. from the driver)."""
    cls = ray_tpu.remote(_GroupStore)
    cls.options(name=f"_collective_{name}", num_cpus=0).remote(world_size)


def init_collective_group(name: str, world_size: int,
                          rank: int) -> CollectiveGroup:
    """Join a group from a participant (reference:
    ``init_collective_group``, collective.py:120)."""
    deadline = time.monotonic() + 30
    while True:
        try:
            return CollectiveGroup(name, world_size, rank)
        except ValueError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)
