"""TPU VM REST API client (``tpu.googleapis.com`` v2).

The real HTTP layer for :class:`ray_tpu.autoscaler.TPUVMNodeProvider`
(reference: the GCP node provider speaking the TPU API,
``autoscaler/_private/gcp/node_provider.py:75-94`` + ``node.py`` GCPTPUNode;
the reference goes through googleapiclient, this speaks REST directly with
urllib — no SDK in the image).

Every call goes through ``self._transport(verb, url, body, headers)`` which
defaults to urllib; tests (and this zero-egress box) inject a fake transport
or construct with ``dry_run=True`` to record requests. Auth is a pluggable
``token_fn`` defaulting to the GCE metadata server (how a head node inside
GCP authenticates without key files).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

API_ROOT = "https://tpu.googleapis.com/v2"
METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                      "instance/service-accounts/default/token")


class TpuApiError(RuntimeError):
    def __init__(self, status: int, message: str):
        self.status = status
        super().__init__(f"TPU API error {status}: {message}")


def metadata_token() -> str:
    """OAuth token from the GCE metadata server (valid on any GCP VM)."""
    req = urllib.request.Request(METADATA_TOKEN_URL,
                                 headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())["access_token"]


def _urllib_transport(verb: str, url: str, body: Optional[dict],
                      headers: Dict[str, str]) -> dict:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=verb,
                                 headers={"Content-Type": "application/json",
                                          **headers})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            payload = resp.read()
            return json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        raise TpuApiError(e.code, e.read().decode(errors="replace")) from e


class TpuVmClient:
    """Typed wrapper over the nodes/operations endpoints the provisioning
    path needs: create (returns a long-running operation), delete, list,
    get, and operation polling."""

    def __init__(
        self,
        project: str,
        zone: str,
        token_fn: Callable[[], str] = metadata_token,
        transport: Optional[Callable] = None,
        dry_run: bool = False,
        api_root: str = API_ROOT,
    ):
        self.parent = f"projects/{project}/locations/{zone}"
        self._root = api_root.rstrip("/")
        self._token_fn = token_fn
        self.dry_run = dry_run
        self.requests: List[Dict[str, Any]] = []  # dry-run/test record
        self._transport = transport or _urllib_transport

    # ------------------------------------------------------------ plumbing

    def _call(self, verb: str, path: str,
              body: Optional[dict] = None) -> dict:
        url = f"{self._root}/{path}"
        self.requests.append({"verb": verb, "path": path, "body": body})
        if self.dry_run:
            return {"name": f"{self.parent}/operations/dry-run",
                    "done": True}
        headers = {"Authorization": f"Bearer {self._token_fn()}"}
        return self._transport(verb, url, body, headers)

    # ------------------------------------------------------------- nodes

    def create_node(
        self,
        node_id: str,
        accelerator_type: str,
        runtime_version: str,
        labels: Optional[Dict[str, str]] = None,
        metadata: Optional[Dict[str, str]] = None,
        network_config: Optional[dict] = None,
        startup_script: Optional[str] = None,
    ) -> dict:
        """POST nodes — creates one pod slice as a single API object (the
        gang atomicity the scheduler's slice bundles rely on). Returns the
        long-running operation."""
        meta = dict(metadata or {})
        if startup_script is not None:
            meta["startup-script"] = startup_script
        body = {
            "acceleratorType": accelerator_type,
            "runtimeVersion": runtime_version,
            "labels": labels or {},
            "metadata": meta,
        }
        if network_config:
            body["networkConfig"] = network_config
        return self._call("POST",
                          f"{self.parent}/nodes?nodeId={node_id}", body)

    def delete_node(self, name: str) -> dict:
        return self._call("DELETE", name)

    def get_node(self, name: str) -> dict:
        return self._call("GET", name)

    def list_nodes(self) -> List[dict]:
        nodes: List[dict] = []
        page = self._call("GET", f"{self.parent}/nodes")
        nodes.extend(page.get("nodes", []))
        while page.get("nextPageToken"):
            page = self._call(
                "GET",
                f"{self.parent}/nodes?pageToken={page['nextPageToken']}")
            nodes.extend(page.get("nodes", []))
        return nodes

    # --------------------------------------------------------- operations

    def wait_operation(self, op: dict, timeout: float = 900.0,
                       poll_s: float = 5.0) -> dict:
        """Poll a long-running operation to completion (create/delete take
        minutes for big slices)."""
        deadline = time.monotonic() + timeout
        while not op.get("done"):
            if time.monotonic() > deadline:
                raise TimeoutError(f"operation {op.get('name')} timed out")
            time.sleep(poll_s)
            op = self._call("GET", op["name"])
        if "error" in op:
            err = op["error"]
            raise TpuApiError(err.get("code", -1),
                              err.get("message", str(err)))
        return op

    # ----------------------------------------------------------- helpers

    @staticmethod
    def node_hosts(node: dict) -> List[str]:
        """Internal IPs of every VM in the slice (for the pod command
        runner; reference: GCPTPUNode.get_internal_ips)."""
        return [ep.get("ipAddress", "")
                for ep in node.get("networkEndpoints", [])]

    @staticmethod
    def node_state(node: dict) -> str:
        return node.get("state", "UNKNOWN")
