"""Tuner: the HPO trial loop.

Analogue of the reference's ``Tuner.fit`` -> ``TuneController`` event loop
(``tune/tuner.py:44,344``, ``tune/execution/tune_controller.py:68,666``):
trials run as actors (via the same TrainWorker session machinery Train
uses — the reference likewise unifies trial and train execution), the
controller polls results, feeds them to the scheduler (FIFO/ASHA/PBT), and
stops / exploits trials per its decisions. PBT exploitation restarts the
trial actor from the donor trial's latest checkpoint with perturbed
hyperparameters (reference: ``pbt.py`` checkpoint clone + perturb).

Train-over-Tune layering (reference: ``train/base_trainer.py:819`` wraps a
trainer as a Tune ``Trainable``; ``tune/execution/placement_groups.py``
gang-places trial resources): ``Tuner(JaxTrainer(...))`` runs each trial as
a full gang-scheduled ``WorkerGroup`` — per-trial placement group, N
workers, optional multi-process jax.distributed mesh — with the trial's
sampled config merged over ``train_loop_config``. ASHA stop and PBT
checkpoint-clone/perturb act on the whole gang. Function trials can also
request a per-trial PG by passing a bundle LIST as ``resources_per_trial``
(bundle 0 hosts the trial; the rest reserve side resources).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.core import serialization
from ray_tpu.core.placement import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.trainer import JaxTrainer
from ray_tpu.train.worker_group import (
    GangReservationError,
    TrainWorker,
    WorkerGroup,
    launch_gang,
)
from ray_tpu.tune.schedulers import CONTINUE, EXPLOIT, STOP, FIFOScheduler
from ray_tpu.tune.search import generate_variants


@dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    search_alg: Any = None  # e.g. tune.TPESearcher; None = grid/random
    seed: int = 0


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Optional[Dict[str, Any]] = None
    checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self.results = results
        self._metric, self._mode = metric, mode

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self.results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def __len__(self):
        return len(self.results)

    def __iter__(self):
        return iter(self.results)


class _Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.id = trial_id
        self.config = dict(config)
        # Execution state (never snapshotted): either one TrainWorker
        # actor (function trial) or a WorkerGroup gang (trainer trial).
        self.actor = None
        self.group: Optional[WorkerGroup] = None
        self.pg = None               # function-trial per-trial PG
        self.workers: List[Any] = []  # long-poll targets; [0] is rank 0
        # Bumped on every (re)launch and stop: outstanding long-poll
        # replies from a previous incarnation are dropped by epoch check.
        self.epoch = 0
        self.state = "PENDING"
        self.iteration = 0
        self.latest_checkpoint: Optional[str] = None
        self.result = TrialResult(trial_id, dict(config))

    def __hash__(self):
        return hash(self.id)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "config": self.config,
            "state": self.state,
            "iteration": self.iteration,
            "latest_checkpoint": self.latest_checkpoint,
            "error": self.result.error,
            "metrics": self.result.metrics,
            "metrics_history": self.result.metrics_history[-50:],
        }

    @classmethod
    def from_snapshot(cls, data: Dict[str, Any]) -> "_Trial":
        trial = cls(data["id"], data["config"])
        trial.state = data["state"]
        trial.iteration = data.get("iteration", 0)
        trial.latest_checkpoint = data.get("latest_checkpoint")
        trial.result.error = data.get("error")
        trial.result.metrics = data.get("metrics")
        trial.result.metrics_history = list(data.get("metrics_history", []))
        if trial.latest_checkpoint:
            trial.result.checkpoint = Checkpoint(trial.latest_checkpoint)
        return trial


class Tuner:
    def __init__(
        self,
        trainable: Union[Callable[[Dict[str, Any]], None], JaxTrainer],
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        resources_per_trial: Optional[
            Union[Dict[str, float], List[Dict[str, float]]]] = None,
        storage_path: Optional[str] = None,
        name: Optional[str] = None,
    ):
        self._trainable = trainable
        self._trainer = trainable if isinstance(trainable, JaxTrainer) \
            else None
        self._param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self._resources = resources_per_trial or {"CPU": 1.0}
        if storage_path is None and self._trainer is not None:
            storage_path = self._trainer.run_config.storage_path
        self._storage = storage_path
        self._name = name or f"tune_{uuid.uuid4().hex[:8]}"
        self._restored_trials: Optional[List[_Trial]] = None

    # --------------------------------------------------- restore/snapshot

    @classmethod
    def restore(cls, path: str, trainable: Union[Callable, JaxTrainer],
                resume_errored: bool = False,
                tune_config: Optional["TuneConfig"] = None) -> "Tuner":
        """Rebuild a Tuner from an experiment-state snapshot so a crashed or
        killed driver can resume its sweep (reference: ``Tuner.restore``,
        ``tune/tuner.py:171`` + ``execution/experiment_state.py``). Finished
        trials keep their results; in-flight trials restart from their
        latest checkpoint; errored trials restart only with
        ``resume_errored``. Schedulers are code, not snapshot state — pass
        ``tune_config`` (with the scheduler) to keep ASHA/PBT decisions
        after restore; otherwise the sweep resumes under FIFO."""
        import json
        import os

        with open(os.path.join(path, "experiment_state.json")) as f:
            state = json.load(f)
        resources = state["resources"]
        tuner = cls(
            trainable,
            param_space={},
            tune_config=tune_config or TuneConfig(**state["tune_config"]),
            resources_per_trial=resources,
            storage_path=state["storage"],
            name=state["name"],
        )
        trials = [_Trial.from_snapshot(t) for t in state["trials"]]
        for trial in trials:
            if trial.state in ("RUNNING", "PENDING"):
                trial.state = "PENDING"
            elif trial.state == "ERROR" and resume_errored:
                trial.state = "PENDING"
                trial.result.error = None
        tuner._restored_trials = trials
        return tuner

    def _experiment_dir(self) -> Optional[str]:
        import os

        if self._storage is None:
            return None
        path = os.path.join(self._storage, self._name)
        os.makedirs(path, exist_ok=True)
        return path

    def _save_state(self, trials: List[_Trial]) -> None:
        import json
        import os

        path = self._experiment_dir()
        if path is None:
            return
        tc = self.tune_config
        state = {
            "name": self._name,
            "storage": self._storage,
            "resources": self._resources,
            "tune_config": {"metric": tc.metric, "mode": tc.mode,
                            "num_samples": tc.num_samples,
                            "max_concurrent_trials": tc.max_concurrent_trials,
                            "seed": tc.seed},
            "trials": [t.snapshot() for t in trials],
        }
        tmp = os.path.join(path, "experiment_state.json.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, os.path.join(path, "experiment_state.json"))

    # ------------------------------------------------------------- fit

    def fit(self) -> ResultGrid:
        from ray_tpu import usage as _usage

        _usage.record_feature("tune.Tuner")
        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        searcher = tc.search_alg
        if self._restored_trials is not None:
            trials = self._restored_trials
            searcher = None  # restored sweeps replay their saved configs
        elif searcher is not None:
            # Model-based search: configs are SUGGESTED one at a time as
            # slots free up, informed by completed trials (reference:
            # Optuna/HyperOpt searcher seam, tune/search/searcher.py).
            searcher.set_search_properties(tc.metric, tc.mode,
                                           self._param_space)
            trials = []
        else:
            variants = generate_variants(self._param_space, tc.num_samples,
                                         tc.seed)
            trials = [_Trial(f"{self._name}_{i:05d}", cfg)
                      for i, cfg in enumerate(variants)]
        train_fn = (self._trainer._train_fn if self._trainer is not None
                    else self._trainable)
        fn_blob = serialization.dumps_function(train_fn)
        if tc.max_concurrent_trials:
            max_conc = tc.max_concurrent_trials
        elif searcher is not None:
            # Unbounded concurrency would suggest the whole sweep before
            # any result lands, degenerating model-based search to random.
            max_conc = 4
        else:
            max_conc = max(len(trials), tc.num_samples, 1)

        pending = [t for t in trials if t.state == "PENDING"]
        running: List[_Trial] = []
        # Long-poll replies in flight: ref -> (trial, worker, epoch). A
        # stale epoch (trial exploited/stopped/restarted) is ignored.
        waiting: Dict[Any, tuple] = {}

        def arm(trial: _Trial, workers: Optional[List[Any]] = None) -> None:
            for w in (workers if workers is not None else trial.workers):
                waiting[w.wait_status.remote(10.0)] = (trial, w, trial.epoch)

        def more_to_suggest() -> bool:
            return searcher is not None and len(trials) < tc.num_samples

        # Set after a failed gang reservation; cleared when a running
        # trial finishes (frees its PG) or by the bounded idle retry below
        # — so an unplaceable trial doesn't churn 60s pg.ready() attempts
        # against the controller on every loop pass.
        reserve_blocked = False
        idle_reserve_retries = 0

        def finish(trial: _Trial) -> None:
            nonlocal reserve_blocked
            reserve_blocked = False
            if trial in running:
                running.remove(trial)
            if searcher is not None:
                searcher.on_trial_complete(trial.id, trial.result.metrics)
            self._save_state(trials)

        self._save_state(trials)
        while pending or running or more_to_suggest():
            while (len(running) < max_conc and not reserve_blocked
                   and (pending or more_to_suggest())):
                if pending:
                    trial = pending.pop(0)
                else:
                    tid = f"{self._name}_{len(trials):05d}"
                    trial = _Trial(tid, searcher.suggest(tid))
                    trials.append(trial)
                try:
                    self._launch(trial, fn_blob)
                except GangReservationError:
                    # Cluster can't fit another gang right now: requeue
                    # and wait for a running trial to free its PG.
                    pending.append(trial)
                    reserve_blocked = True
                    break
                except Exception as e:
                    self._stop_trial(trial)  # free a reserved PG, if any
                    trial.state = "ERROR"
                    trial.result.error = f"trial launch failed: {e}"
                    continue
                idle_reserve_retries = 0
                running.append(trial)
                arm(trial)
            if not waiting:
                if running:
                    time.sleep(0.05)
                    continue
                if pending:
                    # Nothing running to free resources. The shortage can
                    # still be transient (autoscaler bringing up a node,
                    # external actors finishing) — retry with backoff a
                    # few times before declaring the sweep unplaceable.
                    idle_reserve_retries += 1
                    if idle_reserve_retries >= 4:
                        for trial in pending:
                            trial.state = "ERROR"
                            trial.result.error = (
                                "cannot gang-reserve trial resources and "
                                "no running trial will free any")
                        pending.clear()
                        continue
                    time.sleep(5.0 * idle_reserve_retries)
                    reserve_blocked = False
                continue
            ready, _ = ray_tpu.wait(list(waiting), num_returns=1,
                                    timeout=60.0)
            for ref in ready:
                trial, worker, epoch = waiting.pop(ref)
                if trial.epoch != epoch or trial.state != "RUNNING":
                    continue  # exploited/restarted/stopped since this poll
                verdict = self._consume(trial, ref, worker, scheduler,
                                        fn_blob)
                if verdict == "continue":
                    arm(trial, [worker])
                elif verdict == "exploited":
                    arm(trial)  # fresh gang, re-arm every new worker
                elif verdict == "worker_finished":
                    pass  # non-rank-0 done; rank 0 decides the trial
                else:  # terminal
                    finish(trial)
        self._save_state(trials)
        return ResultGrid([t.result for t in trials], tc.metric, tc.mode)

    # --------------------------------------------------------- internals

    def _launch(self, trial: _Trial, fn_blob: bytes,
                checkpoint: Optional[str] = None) -> None:
        trial.epoch += 1
        start_ckpt = checkpoint or trial.latest_checkpoint
        experiment = f"{self._name}/{trial.id}"
        if self._trainer is not None:
            # Gang trial: the trial REQUESTS a gang through the shared
            # launch path (worker_group.launch_gang — the same code
            # trainer attempts use): per-trial PG, N workers, and the
            # optional multi-process jax.distributed bootstrap routed
            # through core/multihost.py (group registration +
            # bootstrap-hash barrier) instead of hand-rolled
            # coordinator/env wiring here. The trial's sampled config
            # merges over train_loop_config (reference:
            # base_trainer.py:608 config-merge into the trainable).
            group = launch_gang(
                self._trainer.scaling_config, self._storage, experiment,
                start_ckpt,
                dataset_shards_per_rank=(
                    self._trainer.dataset_shards_per_rank()))
            try:
                merged = {**(self._trainer._config or {}), **trial.config}
                group.run(None, merged, fn_blob=fn_blob)
            except Exception:
                group.shutdown()
                raise
            trial.group = group
            trial.workers = list(group.workers)
            trial.actor = group.workers[0]
        else:
            actor_cls = ray_tpu.remote(TrainWorker)
            world = {"world_rank": 0, "world_size": 1, "local_rank": 0}
            opts: Dict[str, Any] = {"num_cpus": 0}
            if isinstance(self._resources, (list, tuple)):
                # Per-trial placement group from a bundle list: bundle 0
                # hosts the trial actor, the rest reserve side resources
                # (reference: tune/execution/placement_groups.py
                # PlacementGroupFactory).
                pg = placement_group([dict(b) for b in self._resources],
                                     strategy="PACK")
                if not pg.ready(timeout=60.0):
                    remove_placement_group(pg)
                    raise GangReservationError(
                        f"could not reserve trial bundles "
                        f"{self._resources}")
                trial.pg = pg
                opts["resources"] = dict(self._resources[0])
                opts["scheduling_strategy"] = \
                    PlacementGroupSchedulingStrategy(pg, 0)
            else:
                opts["resources"] = dict(self._resources)
            try:
                trial.actor = actor_cls.options(**opts).remote(
                    world, self._storage, experiment, start_ckpt)
                trial.actor.start.remote(fn_blob, trial.config)
            except Exception:
                if trial.pg is not None:  # don't leak the reserved PG
                    remove_placement_group(trial.pg)
                    trial.pg = None
                raise
            trial.workers = [trial.actor]
        trial.state = "RUNNING"

    def _consume(self, trial: _Trial, status_ref, worker, scheduler,
                 fn_blob: bytes) -> str:
        """Digest one worker's wait_status long-poll reply. Returns
        "continue" (re-arm this worker), "exploited" (gang replaced),
        "worker_finished" (non-rank-0 done), or "terminal"."""
        try:
            status = ray_tpu.get(status_ref, timeout=60)
        except Exception as e:
            trial.result.error = f"trial worker failed: {e}"
            self._stop_trial(trial)
            trial.state = "ERROR"
            return "terminal"
        for r in status["results"]:
            if "error" in r:
                trial.state = "ERROR"
                trial.result.error = r["error"]
                continue
            if r.get("checkpoint"):
                trial.latest_checkpoint = r["checkpoint"]
            if r.get("rank", 0) != 0:
                continue  # metrics/scheduling follow rank 0 only
            trial.iteration += 1
            metrics = dict(r["metrics"])
            metrics.setdefault("training_iteration", trial.iteration)
            trial.result.metrics = metrics
            trial.result.metrics_history.append(metrics)
            trial.result.checkpoint = (
                Checkpoint(trial.latest_checkpoint)
                if trial.latest_checkpoint else None)
            decision = scheduler.on_result(trial, metrics)
            if decision == STOP:
                self._stop_trial(trial)
                trial.state = "TERMINATED"
                return "terminal"
            if decision == EXPLOIT:
                donor = scheduler.exploit_target(trial)
                if donor is not None and donor.latest_checkpoint:
                    if self._exploit(trial, donor, scheduler, fn_blob):
                        return "exploited"
                    return "terminal"
        if trial.state == "ERROR" or status["error"]:
            if status["error"] and trial.result.error is None:
                trial.result.error = status["error"]
            self._stop_trial(trial)
            trial.state = "ERROR"
            return "terminal"
        if status["finished"]:
            if not trial.workers or worker is trial.workers[0]:
                self._stop_trial(trial)
                trial.state = "TERMINATED"
                return "terminal"
            return "worker_finished"
        return "continue"

    def _exploit(self, trial: _Trial, donor: _Trial, scheduler,
                 fn_blob: bytes) -> bool:
        """PBT exploit: restart this trial (actor or whole gang) from the
        donor's checkpoint with perturbed config."""
        self._stop_trial(trial)
        trial.config = scheduler.perturb_config(donor.config)
        trial.result.config = dict(trial.config)
        trial.latest_checkpoint = donor.latest_checkpoint
        try:
            self._launch(trial, fn_blob,
                         checkpoint=donor.latest_checkpoint)
        except Exception as e:
            trial.state = "ERROR"
            trial.result.error = f"exploit relaunch failed: {e}"
            return False
        return True

    def _stop_trial(self, trial: _Trial) -> None:
        trial.epoch += 1  # drop every outstanding long-poll for this trial
        if trial.group is not None:
            try:
                trial.group.shutdown()
            except Exception:  # graftlint: disable=swallowed-exception (best-effort trial teardown; cluster reaps the actor)
                pass
            trial.group = None
        elif trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:  # graftlint: disable=swallowed-exception (best-effort trial teardown; cluster reaps the actor)
                pass
        if trial.pg is not None:
            try:
                remove_placement_group(trial.pg)
            except Exception:  # graftlint: disable=swallowed-exception (best-effort trial teardown; cluster reaps the actor)
                pass
            trial.pg = None
        trial.actor = None
        trial.workers = []
