"""Tuner: the HPO trial loop.

Analogue of the reference's ``Tuner.fit`` -> ``TuneController`` event loop
(``tune/tuner.py:44,344``, ``tune/execution/tune_controller.py:68,666``):
trials run as actors (via the same TrainWorker session machinery Train
uses — the reference likewise unifies trial and train execution), the
controller polls results, feeds them to the scheduler (FIFO/ASHA/PBT), and
stops / exploits trials per its decisions. PBT exploitation restarts the
trial actor from the donor trial's latest checkpoint with perturbed
hyperparameters (reference: ``pbt.py`` checkpoint clone + perturb).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core import serialization
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.worker_group import TrainWorker
from ray_tpu.tune.schedulers import CONTINUE, EXPLOIT, STOP, FIFOScheduler
from ray_tpu.tune.search import generate_variants


@dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    seed: int = 0


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Optional[Dict[str, Any]] = None
    checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self.results = results
        self._metric, self._mode = metric, mode

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self.results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def __len__(self):
        return len(self.results)

    def __iter__(self):
        return iter(self.results)


class _Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.id = trial_id
        self.config = dict(config)
        self.actor = None
        self.state = "PENDING"
        self.iteration = 0
        self.latest_checkpoint: Optional[str] = None
        self.result = TrialResult(trial_id, dict(config))

    def __hash__(self):
        return hash(self.id)


class Tuner:
    def __init__(
        self,
        trainable: Callable[[Dict[str, Any]], None],
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        storage_path: Optional[str] = None,
        name: Optional[str] = None,
    ):
        self._trainable = trainable
        self._param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self._resources = resources_per_trial or {"CPU": 1.0}
        self._storage = storage_path
        self._name = name or f"tune_{uuid.uuid4().hex[:8]}"

    # ------------------------------------------------------------- fit

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        variants = generate_variants(self._param_space, tc.num_samples,
                                     tc.seed)
        trials = [_Trial(f"{self._name}_{i:05d}", cfg)
                  for i, cfg in enumerate(variants)]
        fn_blob = serialization.dumps_function(self._trainable)
        max_conc = tc.max_concurrent_trials or len(trials)

        pending = list(trials)
        running: List[_Trial] = []
        done: List[_Trial] = []
        while pending or running:
            while pending and len(running) < max_conc:
                trial = pending.pop(0)
                self._launch(trial, fn_blob)
                running.append(trial)
            time.sleep(0.05)
            for trial in list(running):
                alive = self._poll(trial, scheduler, fn_blob)
                if not alive:
                    running.remove(trial)
                    done.append(trial)
        return ResultGrid([t.result for t in trials], tc.metric, tc.mode)

    # --------------------------------------------------------- internals

    def _launch(self, trial: _Trial, fn_blob: bytes,
                checkpoint: Optional[str] = None) -> None:
        actor_cls = ray_tpu.remote(TrainWorker)
        world = {"world_rank": 0, "world_size": 1, "local_rank": 0}
        trial.actor = actor_cls.options(
            num_cpus=0, resources=dict(self._resources),
        ).remote(world, self._storage, f"{self._name}/{trial.id}",
                 checkpoint or trial.latest_checkpoint)
        trial.actor.start.remote(fn_blob, trial.config)
        trial.state = "RUNNING"

    def _poll(self, trial: _Trial, scheduler, fn_blob: bytes) -> bool:
        """Returns True while the trial should keep running."""
        try:
            results = ray_tpu.get(trial.actor.next_results.remote(),
                                  timeout=60)
            status = ray_tpu.get(trial.actor.status.remote(), timeout=60)
        except Exception as e:
            trial.state = "ERROR"
            trial.result.error = f"trial actor failed: {e}"
            return False
        for r in results:
            if "error" in r:
                trial.state = "ERROR"
                trial.result.error = r["error"]
                continue
            trial.iteration += 1
            metrics = dict(r["metrics"])
            metrics.setdefault("training_iteration", trial.iteration)
            if r.get("checkpoint"):
                trial.latest_checkpoint = r["checkpoint"]
            trial.result.metrics = metrics
            trial.result.metrics_history.append(metrics)
            trial.result.checkpoint = (
                Checkpoint(trial.latest_checkpoint)
                if trial.latest_checkpoint else None)
            decision = scheduler.on_result(trial, metrics)
            if decision == STOP:
                self._stop_actor(trial)
                trial.state = "TERMINATED"
                return False
            if decision == EXPLOIT:
                donor = scheduler.exploit_target(trial)
                if donor is not None and donor.latest_checkpoint:
                    self._exploit(trial, donor, scheduler, fn_blob)
                    return True
        if trial.state == "ERROR" or status["error"]:
            if status["error"] and trial.result.error is None:
                trial.result.error = status["error"]
            self._stop_actor(trial)
            trial.state = "ERROR"
            return False
        if status["finished"]:
            self._stop_actor(trial)
            trial.state = "TERMINATED"
            return False
        return True

    def _exploit(self, trial: _Trial, donor: _Trial, scheduler,
                 fn_blob: bytes) -> None:
        """PBT exploit: restart this trial from the donor's checkpoint with
        perturbed config."""
        self._stop_actor(trial)
        trial.config = scheduler.perturb_config(donor.config)
        trial.result.config = dict(trial.config)
        trial.latest_checkpoint = donor.latest_checkpoint
        self._launch(trial, fn_blob, checkpoint=donor.latest_checkpoint)

    def _stop_actor(self, trial: _Trial) -> None:
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
