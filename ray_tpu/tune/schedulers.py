"""Trial schedulers: FIFO, ASHA, PBT, PB2, median stopping.

Analogues of the reference's ``tune/schedulers/``: ``FIFOScheduler``,
``AsyncHyperBandScheduler`` (``async_hyperband.py`` — asynchronous successive
halving), ``PopulationBasedTraining`` (``pbt.py`` — exploit best trials'
checkpoints + perturb their hyperparams), ``PB2`` (``pb2.py`` — PBT whose
perturbation is GP-UCB-guided instead of random, the better variant for
small populations) and ``MedianStoppingRule`` (``median_stopping_rule.py``).
The controller calls ``on_result(trial, metrics)`` after every report and
acts on the decision.
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"  # PBT: restart from another trial's checkpoint


class FIFOScheduler:
    def on_result(self, trial, metrics: Dict[str, Any]) -> str:
        return CONTINUE


class ASHAScheduler:
    """Asynchronous successive halving (reference:
    ``tune/schedulers/async_hyperband.py``): rungs at
    ``grace_period * reduction_factor**k``; a trial reaching a rung stops
    unless it is in the top ``1/reduction_factor`` of results recorded at
    that rung so far (async — no waiting for full brackets)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4,
                 time_attr: str = "training_iteration"):
        assert mode in ("min", "max")
        self.metric, self.mode = metric, mode
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> recorded metric values
        self._recorded: Dict[int, List[float]] = defaultdict(list)

    def on_result(self, trial, metrics: Dict[str, Any]) -> str:
        t = metrics.get(self.time_attr, 0)
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for rung in self.rungs:
            if t == rung:
                recorded = self._recorded[rung]
                recorded.append(float(value))
                cutoff_idx = max(0, len(recorded) // self.rf)
                ranked = sorted(recorded, reverse=(self.mode == "max"))
                cutoff = ranked[cutoff_idx] if cutoff_idx < len(ranked) \
                    else ranked[-1]
                good = (value <= cutoff if self.mode == "min"
                        else value >= cutoff)
                if not good and len(recorded) >= self.rf:
                    decision = STOP
        return decision


class PopulationBasedTraining:
    """PBT (reference: ``tune/schedulers/pbt.py``): every
    ``perturbation_interval`` iterations, bottom-quantile trials clone a
    top-quantile trial's latest checkpoint and continue with perturbed
    hyperparameters (multiply by 0.8/1.2, or resample from
    ``hyperparam_mutations``)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 time_attr: str = "training_iteration", seed: int = 0):
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        self._latest: Dict[Any, Dict[str, Any]] = {}  # trial -> last metrics
        self._last_perturb: Dict[Any, int] = {}

    def on_result(self, trial, metrics: Dict[str, Any]) -> str:
        self._latest[trial] = metrics
        t = metrics.get(self.time_attr, 0)
        if t - self._last_perturb.get(trial, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial] = t
        ranked = self._ranked_trials()
        if len(ranked) < 2:
            return CONTINUE
        n_q = max(1, int(len(ranked) * self.quantile))
        bottom = ranked[-n_q:]
        if trial in bottom:
            return EXPLOIT
        return CONTINUE

    def _ranked_trials(self):
        scored = [(tr, m.get(self.metric)) for tr, m in self._latest.items()
                  if m.get(self.metric) is not None]
        return [tr for tr, v in sorted(
            scored, key=lambda kv: kv[1], reverse=(self.mode == "max"))]

    def exploit_target(self, trial):
        """Pick a top-quantile trial to clone from."""
        ranked = self._ranked_trials()
        n_q = max(1, int(len(ranked) * self.quantile))
        top = [t for t in ranked[:n_q] if t is not trial]
        return self._rng.choice(top) if top else None

    def perturb_config(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if key not in out:
                continue
            if isinstance(spec, Domain):
                out[key] = spec.sample(self._rng)
            elif isinstance(spec, list):
                out[key] = self._rng.choice(spec)
            elif isinstance(out[key], (int, float)):
                out[key] = out[key] * self._rng.choice([0.8, 1.2])
        return out


class MedianStoppingRule:
    """Stop a trial at step t when its best result so far is worse than the
    median of the other trials' RUNNING AVERAGES at comparable steps
    (reference: ``tune/schedulers/median_stopping_rule.py`` — the
    Vizier-style performance-curve gate). ``grace_period`` results are
    always allowed; the rule arms only once ``min_samples_required`` trials
    have reported."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 1, min_samples_required: int = 3,
                 time_attr: str = "training_iteration",
                 hard_stop: bool = True):
        assert mode in ("min", "max")
        self.metric, self.mode = metric, mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        self.hard_stop = hard_stop
        # trial -> list of metric values in report order
        self._results: Dict[Any, List[float]] = defaultdict(list)

    def _running_avg(self, values: List[float], upto: int) -> float:
        vals = values[:max(1, upto)]
        return sum(vals) / len(vals)

    def on_result(self, trial, metrics: Dict[str, Any]) -> str:
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        self._results[trial].append(float(value))
        t = len(self._results[trial])
        if t <= self.grace:
            return CONTINUE
        others = [v for tr, v in self._results.items()
                  if tr is not trial and v]
        if len(others) + 1 < self.min_samples:
            return CONTINUE
        medians = sorted(self._running_avg(v, t) for v in others)
        if not medians:
            return CONTINUE
        median = medians[len(medians) // 2]
        mine = self._results[trial]
        best = min(mine) if self.mode == "min" else max(mine)
        worse = best > median if self.mode == "min" else best < median
        return STOP if (worse and self.hard_stop) else CONTINUE


class PB2(PopulationBasedTraining):
    """PBT with GP-UCB-guided perturbation (reference:
    ``tune/schedulers/pb2.py``, Parker-Holder et al. 2020): instead of
    resampling/multiplying hyperparams at random, fit a Gaussian process
    over (hyperparams, time) -> metric IMPROVEMENT observed across the
    whole population, and pick the exploiting trial's new config by
    maximizing the UCB acquisition within ``hyperparam_bounds``. With
    4-8 trials (this repo's regime) random perturbation wastes the few
    exploits available; the GP routes them.

    Continuous hyperparams only (the reference's PB2 has the same
    constraint); bounds are {key: (low, high)}. ``log_scale`` keys are
    modeled in log10 space (the right space for learning rates)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[Dict[str, Any]] = None,
                 log_scale: Optional[Iterable[str]] = None,
                 quantile_fraction: float = 0.25,
                 time_attr: str = "training_iteration", seed: int = 0,
                 ucb_kappa: float = 1.5):
        super().__init__(metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction,
                         time_attr=time_attr, seed=seed)
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.log_keys = set(log_scale or ())
        self.kappa = ucb_kappa
        # Observations: (normalized config vector + time, improvement).
        self._obs_x: List[List[float]] = []
        self._obs_y: List[float] = []
        self._prev: Dict[Any, Dict[str, float]] = {}  # trial -> last point

    # ------------------------------------------------------- observations

    def _encode(self, config: Dict[str, Any], t: float) -> List[float]:
        x = []
        for k, (lo, hi) in sorted(self.bounds.items()):
            v = float(config.get(k, lo))
            if k in self.log_keys:
                import math

                v, lo, hi = (math.log10(max(v, 1e-300)),
                             math.log10(max(lo, 1e-300)),
                             math.log10(max(hi, 1e-300)))
            x.append((v - lo) / max(hi - lo, 1e-12))
        x.append(t)
        return x

    def on_result(self, trial, metrics: Dict[str, Any]) -> str:
        value = metrics.get(self.metric)
        t = metrics.get(self.time_attr, 0)
        if value is not None:
            prev = self._prev.get(trial)
            if prev is not None and t > prev["t"]:
                delta = float(value) - prev["value"]
                if self.mode == "min":
                    delta = -delta  # improvement is positive either way
                self._obs_x.append(self._encode(trial.config, prev["t"]))
                self._obs_y.append(delta / max(1.0, t - prev["t"]))
            self._prev[trial] = {"t": t, "value": float(value)}
        return super().on_result(trial, metrics)

    def exploit_target(self, trial):
        donor = super().exploit_target(trial)
        if donor is not None:
            # The exploiting trial's next report jumps to the donor's
            # cloned value — that delta is checkpoint copying, not the
            # new config's merit. Skip one observation interval so the
            # GP never attributes the jump to the perturbed config.
            self._prev.pop(trial, None)
        return donor

    # --------------------------------------------------------- GP + UCB

    def _gp_posterior(self, X, y, Xq):
        """Tiny exact-GP posterior (RBF kernel, unit signal, fixed noise)
        — population-scale data is dozens of points, numpy is plenty."""
        import numpy as np

        X = np.asarray(X, float)
        y = np.asarray(y, float)
        Xq = np.asarray(Xq, float)
        # Normalize time column to [0, 1] so one lengthscale fits all.
        tmax = max(X[:, -1].max(), Xq[:, -1].max(), 1.0)
        X = X.copy()
        Xq = Xq.copy()
        X[:, -1] /= tmax
        Xq[:, -1] /= tmax
        y_mu, y_sd = y.mean(), max(y.std(), 1e-9)
        yn = (y - y_mu) / y_sd
        ls = 0.3

        def k(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-d2 / (2 * ls * ls))

        K = k(X, X) + 1e-2 * np.eye(len(X))
        Kq = k(Xq, X)
        sol = np.linalg.solve(K, yn)
        mu = Kq @ sol
        var = 1.0 - np.einsum("ij,ji->i", Kq, np.linalg.solve(K, Kq.T))
        return mu * y_sd + y_mu, np.sqrt(np.maximum(var, 1e-12)) * y_sd

    def perturb_config(self, config: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        out = dict(config)
        keys = sorted(self.bounds)
        t_now = max((p["t"] for p in self._prev.values()), default=0.0)
        n_cand = 64
        rng = np.random.default_rng(self._rng.randrange(2 ** 31))
        cand_unit = rng.uniform(size=(n_cand, len(keys)))
        if len(self._obs_y) >= 4:
            Xq = np.concatenate(
                [cand_unit, np.full((n_cand, 1), t_now)], axis=1)
            mu, sd = self._gp_posterior(self._obs_x, self._obs_y, Xq)
            best = int(np.argmax(mu + self.kappa * sd))
        else:  # cold start: uniform random within bounds (like reference)
            best = 0
        for j, key in enumerate(keys):
            lo, hi = self.bounds[key]
            u = float(cand_unit[best, j])
            if key in self.log_keys:
                import math

                val = 10 ** (math.log10(lo) + u * (math.log10(hi)
                                                   - math.log10(lo)))
            else:
                val = lo + u * (hi - lo)
            out[key] = val
        return out
