"""Trial schedulers: FIFO, ASHA, PBT.

Analogues of the reference's ``tune/schedulers/``: ``FIFOScheduler``,
``AsyncHyperBandScheduler`` (``async_hyperband.py`` — asynchronous successive
halving) and ``PopulationBasedTraining`` (``pbt.py`` — exploit best trials'
checkpoints + perturb their hyperparams). The controller calls
``on_result(trial, metrics)`` after every report and acts on the decision.
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"  # PBT: restart from another trial's checkpoint


class FIFOScheduler:
    def on_result(self, trial, metrics: Dict[str, Any]) -> str:
        return CONTINUE


class ASHAScheduler:
    """Asynchronous successive halving (reference:
    ``tune/schedulers/async_hyperband.py``): rungs at
    ``grace_period * reduction_factor**k``; a trial reaching a rung stops
    unless it is in the top ``1/reduction_factor`` of results recorded at
    that rung so far (async — no waiting for full brackets)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4,
                 time_attr: str = "training_iteration"):
        assert mode in ("min", "max")
        self.metric, self.mode = metric, mode
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> recorded metric values
        self._recorded: Dict[int, List[float]] = defaultdict(list)

    def on_result(self, trial, metrics: Dict[str, Any]) -> str:
        t = metrics.get(self.time_attr, 0)
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for rung in self.rungs:
            if t == rung:
                recorded = self._recorded[rung]
                recorded.append(float(value))
                cutoff_idx = max(0, len(recorded) // self.rf)
                ranked = sorted(recorded, reverse=(self.mode == "max"))
                cutoff = ranked[cutoff_idx] if cutoff_idx < len(ranked) \
                    else ranked[-1]
                good = (value <= cutoff if self.mode == "min"
                        else value >= cutoff)
                if not good and len(recorded) >= self.rf:
                    decision = STOP
        return decision


class PopulationBasedTraining:
    """PBT (reference: ``tune/schedulers/pbt.py``): every
    ``perturbation_interval`` iterations, bottom-quantile trials clone a
    top-quantile trial's latest checkpoint and continue with perturbed
    hyperparameters (multiply by 0.8/1.2, or resample from
    ``hyperparam_mutations``)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 time_attr: str = "training_iteration", seed: int = 0):
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        self._latest: Dict[Any, Dict[str, Any]] = {}  # trial -> last metrics
        self._last_perturb: Dict[Any, int] = {}

    def on_result(self, trial, metrics: Dict[str, Any]) -> str:
        self._latest[trial] = metrics
        t = metrics.get(self.time_attr, 0)
        if t - self._last_perturb.get(trial, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial] = t
        ranked = self._ranked_trials()
        if len(ranked) < 2:
            return CONTINUE
        n_q = max(1, int(len(ranked) * self.quantile))
        bottom = ranked[-n_q:]
        if trial in bottom:
            return EXPLOIT
        return CONTINUE

    def _ranked_trials(self):
        scored = [(tr, m.get(self.metric)) for tr, m in self._latest.items()
                  if m.get(self.metric) is not None]
        return [tr for tr, v in sorted(
            scored, key=lambda kv: kv[1], reverse=(self.mode == "max"))]

    def exploit_target(self, trial):
        """Pick a top-quantile trial to clone from."""
        ranked = self._ranked_trials()
        n_q = max(1, int(len(ranked) * self.quantile))
        top = [t for t in ranked[:n_q] if t is not trial]
        return self._rng.choice(top) if top else None

    def perturb_config(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if key not in out:
                continue
            if isinstance(spec, Domain):
                out[key] = spec.sample(self._rng)
            elif isinstance(spec, list):
                out[key] = self._rng.choice(spec)
            elif isinstance(out[key], (int, float)):
                out[key] = out[key] * self._rng.choice([0.8, 1.2])
        return out
