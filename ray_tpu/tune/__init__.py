"""ray_tpu.tune: hyperparameter optimization (reference: Ray Tune)."""

from ray_tpu.train.session import report, get_checkpoint  # noqa: F401  (tune.report == train.report)
from ray_tpu.train.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (  # noqa: F401
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.tpe import TPESearcher  # noqa: F401
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner  # noqa: F401
