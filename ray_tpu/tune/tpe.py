"""Native TPE (tree-structured Parzen estimator) searcher.

The reference wraps external libraries for model-based search (Optuna /
HyperOpt — ``tune/search/optuna``, ``tune/search/hyperopt``; both are TPE
under the hood). None of those ship in this image, so the searcher itself
is native: classic 1-D TPE (Bergstra et al., NeurIPS 2011) per parameter —
split observations into good/bad quantiles, model each with a Parzen
(kernel) density, and propose the candidate maximizing l(x)/g(x).

Plugs into :class:`ray_tpu.tune.Tuner` via ``TuneConfig(search_alg=...)``:
the tuner asks ``suggest()`` for each new trial (instead of pre-sampling
the whole sweep) and feeds results back through ``on_trial_complete``, so
later trials concentrate where earlier ones scored well.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search import (
    Categorical,
    Domain,
    GridSearch,
    LogUniform,
    RandInt,
    Uniform,
)


class TPESearcher:
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 n_startup_trials: int = 8,
                 gamma: float = 0.25,
                 n_candidates: int = 24,
                 seed: int = 0):
        self.metric = metric      # default: the TuneConfig's metric
        self.mode = mode
        self.n_startup = n_startup_trials
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._space: Dict[str, Any] = {}
        self._live: Dict[str, Dict[str, Any]] = {}   # trial id -> config
        self._obs: List[Tuple[Dict[str, Any], float]] = []

    # -- tuner protocol

    def set_search_properties(self, metric: str, mode: str,
                              param_space: Dict[str, Any]) -> None:
        self.metric = self.metric or metric
        self.mode = self.mode or mode
        for k, v in param_space.items():
            if isinstance(v, GridSearch):
                raise ValueError(
                    "TPESearcher does not combine with grid_search axes; "
                    "use choice(...) instead")
        self._space = dict(param_space)

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        cfg = {}
        for name, dom in self._space.items():
            if not isinstance(dom, Domain):
                cfg[name] = dom  # constant
            elif len(self._obs) < self.n_startup:
                cfg[name] = dom.sample(self._rng)
            else:
                cfg[name] = self._suggest_one(name, dom)
        self._live[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str,
                          metrics: Optional[Dict[str, Any]]) -> None:
        cfg = self._live.pop(trial_id, None)
        if cfg is None or not metrics or self.metric not in metrics:
            return
        value = float(metrics[self.metric])
        if self.mode == "max":
            value = -value  # internal convention: lower is better
        self._obs.append((cfg, value))

    # -- TPE core

    def _split(self) -> Tuple[list, list]:
        ordered = sorted(self._obs, key=lambda o: o[1])
        n_good = max(1, int(math.ceil(self.gamma * len(ordered))))
        return ordered[:n_good], ordered[n_good:]

    def _suggest_one(self, name: str, dom: Domain):
        good, bad = self._split()
        gvals = [o[0][name] for o in good if name in o[0]]
        bvals = [o[0][name] for o in bad if name in o[0]]
        if isinstance(dom, Categorical):
            return self._categorical(dom, gvals, bvals)
        if isinstance(dom, LogUniform):
            lo, hi = dom.log_low, dom.log_high
            g = [math.log(v) for v in gvals]
            b = [math.log(v) for v in bvals]
            x = self._parzen_pick(lo, hi, g, b)
            return math.exp(x)
        if isinstance(dom, RandInt):
            lo, hi = float(dom.low), float(dom.high - 1)
            x = self._parzen_pick(lo, hi, [float(v) for v in gvals],
                                  [float(v) for v in bvals])
            return int(min(dom.high - 1, max(dom.low, round(x))))
        if isinstance(dom, Uniform):
            return self._parzen_pick(dom.low, dom.high,
                                     [float(v) for v in gvals],
                                     [float(v) for v in bvals])
        return dom.sample(self._rng)

    def _parzen_pick(self, lo: float, hi: float,
                     good: List[float], bad: List[float]) -> float:
        """Draw candidates from the good-density, keep the argmax of
        l(x)/g(x). Bandwidth: range-scaled Scott-ish heuristic with a
        floor, per the original TPE prior smoothing."""
        if not good:
            return self._rng.uniform(lo, hi)
        span = max(hi - lo, 1e-12)
        n = len(good) + len(bad)
        # Scott-flavored bandwidth shrinking with the TOTAL observation
        # count (a lone good point early on must not blow bw up to the
        # whole span), floored for exploration.
        bw = max(span * 0.05, span * 0.5 * max(n, 2) ** -0.4)

        def draw(center):
            # Truncated gaussian by rejection: clamping instead would pile
            # candidate mass onto the bounds and the ratio score would pin
            # suggestions to the boundary.
            for _ in range(20):
                x = self._rng.gauss(center, bw)
                if lo <= x <= hi:
                    return x
            return self._rng.uniform(lo, hi)

        def density(x, centers):
            # + a uniform prior component so unexplored regions keep mass.
            p = 1.0 / span
            for c in centers:
                z = (x - c) / bw
                p += math.exp(-0.5 * z * z) / (bw * 2.5066282746310002)
            return p / (len(centers) + 1)

        best_x, best_score = None, -math.inf
        for _ in range(self.n_candidates):
            x = draw(self._rng.choice(good))
            score = density(x, good) / max(density(x, bad), 1e-12)
            if score > best_score:
                best_x, best_score = x, score
        return best_x

    def _categorical(self, dom: Categorical, gvals, bvals):
        def probs(vals):
            counts = {c: 1.0 for c in dom.categories}  # +1 smoothing
            for v in vals:
                counts[v] = counts.get(v, 1.0) + 1.0
            total = sum(counts.values())
            return {c: counts[c] / total for c in dom.categories}

        pg, pb = probs(gvals), probs(bvals)
        scored = [(pg[c] / max(pb[c], 1e-12), c) for c in dom.categories]
        # Sample proportionally to the likelihood ratio (keeps exploration).
        total = sum(s for s, _c in scored)
        r = self._rng.uniform(0, total)
        for s, c in scored:
            r -= s
            if r <= 0:
                return c
        return scored[-1][1]
