"""Search spaces + variant generation.

Analogue of the reference's sample domains (``tune/search/sample.py``) and
``BasicVariantGenerator`` (grid + random sampling,
``tune/search/basic_variant.py``). Advanced searchers (Optuna/HyperOpt/...)
are external-library wrappers in the reference; the native core is this.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_low, self.log_high))


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def generate_variants(param_space: Dict[str, Any], num_samples: int,
                      seed: int = 0) -> List[Dict[str, Any]]:
    """Expand grid axes (cross product), then draw ``num_samples`` of the
    random domains for each grid point (reference semantics: num_samples
    multiplies the grid)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items()
                 if isinstance(v, GridSearch)]
    grids: List[Dict[str, Any]] = [{}]
    for k in grid_keys:
        grids = [dict(g, **{k: val}) for g in grids
                 for val in param_space[k].values]
    variants = []
    for g in grids:
        for _ in range(num_samples):
            cfg = dict(g)
            for k, v in param_space.items():
                if k in cfg:
                    continue
                cfg[k] = v.sample(rng) if isinstance(v, Domain) else v
            variants.append(cfg)
    return variants
