"""Command runners: execute bootstrap/setup commands on cluster nodes.

Analogue of the reference's ``command_runner.py`` (SSHCommandRunner,
DockerCommandRunner) and ``tpu_command_runner.py`` (fan the same command out
to every host of a TPU pod slice) used by the node updater
(``autoscaler/_private/updater.py``) during ``ray up``.

Every runner supports ``dry_run``: the exact argv it would execute is
recorded on ``.history`` instead of spawned — this box has zero egress, so
the SSH paths are exercised in tests via dry-run (the reference tests its
command runners the same way: assert on the built command line).
"""

from __future__ import annotations

import shlex
import subprocess
from typing import Dict, List, Optional, Sequence


class CommandFailed(RuntimeError):
    def __init__(self, cmd: Sequence[str], rc: int, output: str):
        self.cmd = list(cmd)
        self.rc = rc
        self.output = output
        super().__init__(f"command {cmd!r} exited {rc}: {output[-500:]}")


class CommandRunner:
    """One target node. ``run`` executes a shell command; ``put`` ships a
    local file to the node."""

    def __init__(self, dry_run: bool = False):
        self.dry_run = dry_run
        self.history: List[List[str]] = []

    def _execute(self, argv: Sequence[str], timeout: float) -> str:
        self.history.append(list(argv))
        if self.dry_run:
            return ""
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise CommandFailed(argv, proc.returncode,
                                proc.stderr or proc.stdout)
        return proc.stdout

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        raise NotImplementedError

    def put(self, src: str, dst: str, timeout: float = 600.0) -> None:
        raise NotImplementedError


class SubprocessCommandRunner(CommandRunner):
    """Local execution (fake/local providers; also the head bootstrapping
    itself)."""

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        return self._execute(["bash", "-lc", cmd], timeout)

    def put(self, src: str, dst: str, timeout: float = 600.0) -> None:
        self._execute(["cp", src, dst], timeout)


class SSHCommandRunner(CommandRunner):
    """SSH to one host (reference: ``command_runner.py`` SSHCommandRunner —
    same knobs: user, key file, strict-host-key off for fresh VMs)."""

    def __init__(self, host: str, user: str = "ray",
                 key_file: Optional[str] = None, dry_run: bool = False,
                 ssh_options: Optional[List[str]] = None):
        super().__init__(dry_run)
        self.host = host
        self.user = user
        self.key_file = key_file
        self._options = list(ssh_options or [
            "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "ConnectTimeout=10",
        ])

    def _base(self, prog: str) -> List[str]:
        argv = [prog] + self._options
        if self.key_file:
            argv += ["-i", self.key_file]
        return argv

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        # shlex.quote, not repr: commands routinely mix quote styles
        # (--resources '{"CPU": 4}') and repr's \' is NOT an escape inside
        # POSIX single quotes.
        argv = self._base("ssh") + [f"{self.user}@{self.host}",
                                    f"bash -lc {shlex.quote(cmd)}"]
        return self._execute(argv, timeout)

    def put(self, src: str, dst: str, timeout: float = 600.0) -> None:
        argv = self._base("scp") + [src, f"{self.user}@{self.host}:{dst}"]
        self._execute(argv, timeout)


class TPUPodCommandRunner(CommandRunner):
    """Fan a command out to every host of a TPU pod slice (reference:
    ``tpu_command_runner.py`` — a TPU "node" is N VMs; setup and ray-start
    must run on all of them). Hosts come from the TPU VM API's
    ``networkEndpoints``."""

    def __init__(self, hosts: List[str], user: str = "ray",
                 key_file: Optional[str] = None, dry_run: bool = False):
        super().__init__(dry_run)
        self.workers = [SSHCommandRunner(h, user, key_file, dry_run)
                        for h in hosts]

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        outs = []
        for w in self.workers:
            outs.append(w.run(cmd, timeout))
            self.history.append(w.history[-1])
        return "\n".join(outs)

    def run_per_host(self, cmd_template: str,
                     env_per_host: List[Dict[str, str]],
                     timeout: float = 600.0) -> List[str]:
        """Run a templated command with per-host env (worker index, count —
        how ``ray start`` gets its rank on each slice host)."""
        outs = []
        for w, env in zip(self.workers, env_per_host):
            exports = " ".join(f"{k}={v}" for k, v in env.items())
            cmd = f"{exports} {cmd_template}" if exports else cmd_template
            outs.append(w.run(cmd, timeout))
            self.history.append(w.history[-1])
        return outs

    def put(self, src: str, dst: str, timeout: float = 600.0) -> None:
        for w in self.workers:
            w.put(src, dst, timeout)
            self.history.append(w.history[-1])
