"""Engine step timeline: a bounded ring answering "why was THIS token
slow?".

The SLO histograms say a p99 token took 300 ms; this recorder says what
the engine was doing at that moment: one row per ``DecodeEngine.step()``
with the step's phases (admission prefill, interleaved prefill chunk,
decode, in spec mode ``draft``/``verify`` — the verify phase carries
the round's accepted-token count — and on a disaggregated decode fleet
``handoff``, the adopt splice of a prefill fleet's published KV pages,
tagged with the page count) and batch occupancy, plus
the discrete events that explain latency cliffs — page alloc/free,
recompute preemption, draft-seat demotions (``spec-draftless``), jit
compiles (first dispatch of a program key).

Recording is a deque append + a few ``monotonic()`` reads per STEP
(never per token), so the decode loop pays microseconds against a
device call that costs milliseconds. The ring is host memory only; it
is dumped on demand through ``engine.timeline()`` -> the replica RPC ->
``python -m ray_tpu timeline --serve``, which merges every replica's
rows into the cross-process Chrome trace.

Timestamps are wall-clock (``time.time``) so rows align with the task
-event spans in the same trace; phase durations are measured with the
same clock (the ~us drift vs monotonic is far below a step).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional


class StepTimeline:
    """Bounded per-engine step recorder. Not thread-safe by design: it
    is only touched from the engine's decode-loop thread; ``dump()``
    snapshots via list() which is atomic enough for a diagnostic read
    from the actor RPC thread (rows are immutable once appended)."""

    __slots__ = ("capacity", "_rows", "_events", "dropped")

    def __init__(self, capacity: int = 256):
        self.capacity = max(0, int(capacity))
        self._rows: deque = deque(maxlen=self.capacity or None)
        self._events: List[Dict[str, Any]] = []  # pending, next row's
        self.dropped = 0

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    @property
    def pending_events(self) -> bool:
        return bool(self._events)

    # ------------------------------------------------------------ events

    def event(self, kind: str, **attrs: Any) -> None:
        """Queue a discrete event (page alloc/free, preempt, jit
        compile); it attaches to the next recorded step row."""
        if not self.capacity:
            return
        e = {"kind": kind, "ts": time.time()}
        if attrs:
            e.update(attrs)
        self._events.append(e)

    # -------------------------------------------------------------- rows

    def record(self, step: int, t0: float, t1: float, phases:
               List[Dict[str, Any]], active: int, prefilling: int,
               queued: int, pages_free: Optional[int] = None) -> None:
        """One engine step: ``phases`` are the step's timed sub-slices
        ([{phase, t0, t1, ...attrs}]); occupancy is sampled at the step
        boundary; queued events ride along and clear."""
        if not self.capacity:
            self._events.clear()
            return
        if len(self._rows) == self._rows.maxlen:
            self.dropped += 1
        row = {"step": step, "t0": t0, "t1": t1, "phases": phases,
               "active": active, "prefilling": prefilling,
               "queued": queued}
        if pages_free is not None:
            row["pages_free"] = pages_free
        if self._events:
            row["events"] = self._events
            self._events = []
        self._rows.append(row)

    def dump(self) -> Dict[str, Any]:
        return {"capacity": self.capacity, "dropped": self.dropped,
                "rows": list(self._rows)}


def timeline_chrome_events(dump: Dict[str, Any], pid: str
                           ) -> List[Dict[str, Any]]:
    """Render one engine's timeline dump as Chrome trace events: phase
    slices on an ``engine-step`` track, occupancy as counters, discrete
    events as instants. Shared by the timeline CLI and trace-demo."""
    out: List[Dict[str, Any]] = []
    for row in dump.get("rows", []):
        for ph in row.get("phases", []):
            out.append({
                "name": ph.get("phase", "step"),
                "cat": "engine-step", "ph": "X",
                "ts": ph["t0"] * 1e6,
                "dur": max(0.0, (ph["t1"] - ph["t0"]) * 1e6),
                "pid": pid, "tid": "engine-step",
                "args": {k: v for k, v in ph.items()
                         if k not in ("phase", "t0", "t1")},
            })
        out.append({
            "name": "occupancy", "ph": "C", "pid": pid,
            "ts": row["t0"] * 1e6,
            "args": {"active": row.get("active", 0),
                     "prefilling": row.get("prefilling", 0),
                     "queued": row.get("queued", 0)},
        })
        for e in row.get("events", []):
            out.append({
                "name": e.get("kind", "event"), "cat": "engine-event",
                "ph": "i", "s": "t", "ts": e.get("ts", row["t0"]) * 1e6,
                "pid": pid, "tid": "engine-step",
                "args": {k: v for k, v in e.items()
                         if k not in ("kind", "ts")},
            })
    return out
