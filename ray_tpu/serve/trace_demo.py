"""Serve-plane trace demo: ``make trace-demo`` / tier-1's fast gate.

Runs a tiny serve session (debug-model decode deployment, two
replicas, real HTTP proxy), issues traced requests through the proxy
with the client's own span propagated via ``X-Trace-Id`` headers,
merges the task-event spans with every replica's engine step timeline
into one Chrome trace JSON, and VALIDATES it: the file must load as
JSON and contain at least one cross-process parent/child span pair —
the invariant that makes the trace causally linked rather than a pile
of disconnected slices.

Standalone::

    python -m ray_tpu.serve.trace_demo [--output /tmp/serve_trace.json]

Inside an existing cluster (the tier-1 test): call :func:`run_demo`
with ``init=False``.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Any, Dict, List, Optional, Tuple


def validate_trace(trace: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Causality audit of a Chrome trace built by
    ``scripts.build_chrome_trace``: span process spread and
    cross-process parent/child links (a child span whose parent span
    was recorded by a DIFFERENT process)."""
    spans = [t for t in trace if t.get("cat") == "span"]
    by_id = {t["args"]["span_id"]: t for t in spans
             if t.get("args", {}).get("span_id")}
    cross: List[Tuple[str, str]] = []
    for t in spans:
        parent = t.get("args", {}).get("parent_span")
        p = by_id.get(parent)
        if p is not None and p["pid"] != t["pid"]:
            cross.append((p["name"], t["name"]))
    return {
        "events": len(trace),
        "spans": len(spans),
        "span_pids": sorted({t["pid"] for t in spans}),
        "engine_slices": sum(1 for t in trace
                             if t.get("cat") == "engine-step"),
        "cross_process_links": cross,
    }


def run_demo(output: Optional[str] = None, init: bool = True,
             replicas: int = 2, requests: int = 3,
             timeout_s: float = 120.0) -> Dict[str, Any]:
    """Run the demo; returns ``validate_trace``'s report (raises when
    the trace fails validation). ``init=False`` reuses the caller's
    cluster (tests)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.runtime import get_core_worker
    from ray_tpu.scripts import build_chrome_trace
    from ray_tpu.serve.decode import LlamaDecodeDeployment
    from ray_tpu.util import tracing

    if init:
        ray_tpu.init(num_cpus=4)
    try:
        app = serve.deployment(num_replicas=replicas)(
            LlamaDecodeDeployment).bind(preset="debug", slots=2,
                                        capacity=128)
        serve.run(app, name="trace_demo")
        host, port = serve.start_http()
        url = f"http://{host}:{port}/trace_demo"
        for i in range(requests):
            with tracing.trace("client-request", i=i):
                ctx = tracing.current()
                req = urllib.request.Request(
                    url,
                    data=json.dumps({"tokens": [1, 2, 3, 4 + i],
                                     "max_new_tokens": 4}).encode(),
                    headers={"Content-Type": "application/json",
                             "X-Trace-Id": ctx[0],
                             "X-Parent-Span": ctx[1]})
                with urllib.request.urlopen(req, timeout=60) as resp:
                    json.loads(resp.read())
        core = get_core_worker()
        # Spans flush on each process's own cadence; poll until the
        # trace validates (or the deadline names what's missing).
        deadline = time.monotonic() + timeout_s
        report: Dict[str, Any] = {}
        trace: List[Dict[str, Any]] = []
        while time.monotonic() < deadline:
            core._flush_task_events()
            events = core.controller.call("list_task_events", 10000)
            trace = build_chrome_trace(events, serve.timelines())
            report = validate_trace(trace)
            if (len(report["span_pids"]) >= 3
                    and report["cross_process_links"]
                    and report["engine_slices"] >= 1):
                break
            time.sleep(0.3)
        if output:
            with open(output, "w") as f:
                json.dump(trace, f)
            with open(output) as f:
                json.load(f)  # the artifact itself must round-trip
            report["output"] = output
        if len(report.get("span_pids", [])) < 3:
            raise AssertionError(
                f"spans from {report.get('span_pids')} — expected >=3 "
                f"processes (client, proxy/router, replica engine)")
        if not report.get("cross_process_links"):
            raise AssertionError(
                "no cross-process parent/child span pair in the trace")
        if report.get("engine_slices", 0) < 1:
            raise AssertionError("no engine step-timeline slices merged")
        return report
    finally:
        try:
            serve.shutdown()
        finally:
            if init:
                ray_tpu.shutdown()


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.serve.trace_demo")
    parser.add_argument("--output", "-o", default="serve_trace.json")
    args = parser.parse_args(argv)
    report = run_demo(output=args.output)
    print(json.dumps(report, indent=2))
    print(f"trace OK: {report['spans']} spans across "
          f"{len(report['span_pids'])} processes, "
          f"{len(report['cross_process_links'])} cross-process links, "
          f"{report['engine_slices']} engine slices -> {args.output}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
