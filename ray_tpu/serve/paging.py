"""Host-side page allocator + paged prefix index for the decode plane.

The paging half of the serve plane's memory story (``serve/decode.py``
owns the device arrays and jitted programs; ``models/llama_decode.py``
owns the paged attention math). Two pieces:

* ``PageAllocator`` — a refcounted free-list over device pool page ids.
  A page is handed out with refcount 1; sharing (prefix splices, prefix-
  index pins) increfs it; ``free`` decrefs and recycles at zero. Pages
  with refcount > 1 are never written by construction — sharing is
  full-page-aligned and sequence writes are append-only past the shared
  region — which is the copy-on-write discipline without ever needing
  the copy.
* ``PagedPrefixIndex`` — vLLM-style hash-chained prefix cache: one entry
  per page-aligned prefix length, keyed by the hash of ALL tokens up to
  that page's end, each pinning exactly ONE pool page. Inserting a
  completed prompt is ZERO-COPY: the slot's own pages are increfed and
  recorded (no device traffic at all — contrast PR 2's whole-row pool,
  which copied ``C_prefix`` tokens of K/V per insert and pinned a full
  capacity-sized row per entry). A hit splices page ids into the new
  request's block table; eviction unpins page-granular TAIL segments
  (leaf entries first), so a long cached prefix shrinks gracefully
  instead of vanishing whole.

Single-threaded by design: every caller runs on the engine's decode
loop thread (admission, finish, eviction, reclaim). Cross-thread readers
(stats) only see int counters.

graftlint's resource-lifetime checker knows this module's idiom
(``rules.RESOURCE_POOL_ATTRS``): ``pages = self._pages.alloc(n)`` is an
acquire that must be freed (``self._pages.free(pages)``) or ownership-
transferred on every path.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from ray_tpu.serve.prefix_cache import prefix_hash

SCRATCH_PAGE = 0  # reserved pool page for pad writes; never allocated


class PageAllocator:
    """Refcounted free-list over pool page ids ``1..pages`` (id 0 is the
    scratch page the jitted programs use for pad writes)."""

    def __init__(self, pages: int):
        if pages < 1:
            raise ValueError(f"need at least one pool page, got {pages}")
        self.pages = int(pages)
        # LIFO free list: recently-freed pages are re-used first (their
        # junk contents are provably dead — the program that freed them
        # was dispatched before any program that re-reads them).
        self._free_ids: List[int] = list(range(self.pages, 0, -1))
        self._ref: Dict[int, int] = {}

    # ------------------------------------------------------------ alloc

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages with refcount 1 each, or None (all-or-nothing —
        a partial grant would leave the caller holding pages it cannot
        use but must remember to free)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if len(self._free_ids) < n:
            return None
        out = [self._free_ids.pop() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        return out

    def incref(self, page: int) -> None:
        self._ref[page] += 1  # KeyError on a free page = caller bug

    def free(self, pages: Iterable[int]) -> None:
        """Drop one reference per page; refcount 0 recycles the page."""
        for p in pages:
            r = self._ref[p] - 1
            if r == 0:
                del self._ref[p]
                self._free_ids.append(p)
            else:
                self._ref[p] = r

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    # ------------------------------------------------------------ stats

    @property
    def free_count(self) -> int:
        return len(self._free_ids)

    @property
    def in_use(self) -> int:
        return self.pages - len(self._free_ids)

    def stats(self) -> Dict[str, int]:
        return {"pages_total": self.pages,
                "pages_free": len(self._free_ids),
                "pages_in_use": self.in_use}


class _PageEntry:
    __slots__ = ("key", "page", "tokens", "length", "parent", "children",
                 "last_used")

    def __init__(self, key: str, page: int, tokens: np.ndarray,
                 length: int, parent: Optional[str]):
        self.key = key          # prefix_hash(tokens[:length])
        self.page = page        # the ONE pool page this entry pins
        self.tokens = tokens    # full prefix tokens, (length,)
        self.length = length    # page-aligned prefix length
        self.parent = parent    # key of the (length - T) entry, if any
        self.children = 0       # longer entries chaining through this one
        self.last_used = 0


class PagedPrefixIndex:
    """Hash-chained page-granular prefix cache over a ``PageAllocator``.

    One entry per page-aligned prefix length: the entry for length
    ``i*T`` is keyed by ``prefix_hash(tokens[:i*T])`` and pins the page
    holding positions ``(i-1)*T .. i*T-1``. ``match`` walks the chain
    page by page and hands back the page ids ALREADY INCREFED for the
    caller's block table (the caller owns one reference per page and
    releases by freeing them with its slot — there is no separate
    release step, unlike PR 2's entry pins). ``insert`` pins a completed
    slot's own pages (zero-copy). Eviction drops LEAF entries (no longer
    chain through them) in LRU order, freeing tail pages first."""

    def __init__(self, allocator: PageAllocator, page_tokens: int,
                 max_pages: int, min_tokens: int = 16):
        self._alloc = allocator
        self.page_tokens = int(page_tokens)
        self.max_pages = max(1, int(max_pages))
        self.min_tokens = max(1, int(min_tokens))
        self._by_key: Dict[str, _PageEntry] = {}
        self._clock = 0
        self.queries = 0
        self.hits = 0
        self.tokens_matched = 0
        self.inserts = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._by_key)

    @property
    def pinned_pages(self) -> int:
        return len(self._by_key)

    def pinned_page_ids(self) -> List[int]:
        """Snapshot of the pool pages this index pins (stats use)."""
        return [ent.page for ent in list(self._by_key.values())]

    # ----------------------------------------------------------- match

    def match(self, tokens) -> Optional[Tuple[List[int], int]]:
        """Longest page-aligned cached prefix: ``(page_ids,
        matched_len)`` with every page already increfed for the caller,
        or None. Capped at ``len(tokens) - 1`` so at least one real
        suffix token remains to produce next-token logits."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        self.queries += 1
        T = self.page_tokens
        limit = (len(toks) - 1) // T
        pages: List[int] = []
        self._clock += 1
        depth = 0
        while depth < limit:
            end = (depth + 1) * T
            ent = self._by_key.get(prefix_hash(toks[:end]))
            if ent is None or not np.array_equal(ent.tokens[:end],
                                                 toks[:end]):
                break  # hash miss (or collision: verify the raw tokens)
            ent.last_used = self._clock
            pages.append(ent.page)
            depth += 1
        matched = depth * T
        if matched < self.min_tokens or not pages:
            return None
        for p in pages:
            self._alloc.incref(p)
        self.hits += 1
        self.tokens_matched += matched
        return pages, matched

    # ---------------------------------------------------------- insert

    def insert(self, tokens, slot_pages: List[int],
               matched_len: int = 0) -> int:
        """Offer a completed prompt's resident pages to the index.
        ``slot_pages[i]`` must back positions ``i*T .. (i+1)*T - 1`` of
        ``tokens``. Pins (increfs) the pages of every NEW entry — zero
        device copies. Returns the number of entries created.

        The insert length is the largest power of two <= the prompt
        length (>= max(min_tokens, T)): the same grid the router's
        affinity hashes probe, kept so hot prefixes dedup across
        replicas. ``matched_len`` gating as in PR 2: skip unless
        coverage at least doubles (per-request random suffixes must not
        thrash the index)."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        T = self.page_tokens
        ins_len = 1
        while ins_len * 2 <= len(toks):
            ins_len *= 2
        if ins_len < max(self.min_tokens, T) or matched_len * 2 >= ins_len:
            return 0
        created = 0
        parent: Optional[str] = None
        self._clock += 1
        for i in range(ins_len // T):
            end = (i + 1) * T
            key = prefix_hash(toks[:end])
            ent = self._by_key.get(key)
            if ent is not None:
                ent.last_used = self._clock  # dedup: refresh recency
                parent = key
                continue
            page = slot_pages[i]
            ent = _PageEntry(key, page, np.array(toks[:end], np.int32),
                             end, parent)
            self._alloc.incref(page)
            if parent is not None:
                self._by_key[parent].children += 1
            ent.last_used = self._clock
            self._by_key[key] = ent
            created += 1
            parent = key
        if created:
            self.inserts += 1
            over = self.pinned_pages - self.max_pages
            if over > 0:
                self.reclaim(over, only_free=False)
        return created

    # --------------------------------------------------------- eviction

    def reclaim(self, n_pages: int, only_free: bool = True) -> int:
        """Unpin up to ``n_pages`` pages, LRU leaf entries first (tail
        segments of a chain shrink before its head — a shortened prefix
        is still a valid, shorter prefix). ``only_free`` restricts to
        pages this index holds the LAST reference to (the allocation-
        pressure path: unpinning a page a live slot still borrows frees
        nothing). Returns pages actually unpinned."""
        done = 0
        while done < n_pages:
            victim: Optional[_PageEntry] = None
            for ent in self._by_key.values():
                if ent.children:
                    continue
                if only_free and self._alloc.refcount(ent.page) != 1:
                    continue
                if victim is None or ent.last_used < victim.last_used:
                    victim = ent
            if victim is None:
                break
            self._evict(victim)
            done += 1
        return done

    def _evict(self, ent: _PageEntry) -> None:
        del self._by_key[ent.key]
        if ent.parent is not None:
            parent = self._by_key.get(ent.parent)
            if parent is not None:
                parent.children -= 1
        self._alloc.free((ent.page,))
        self.evictions += 1

    # ------------------------------------------------------------ stats

    def hashes(self) -> List[str]:
        """Entry hashes at power-of-two lengths — the router's affinity
        grid (``candidate_hashes`` probes pow2 leading buckets, so only
        those chain links are discoverable from a raw prompt). Called
        from the replica stats thread while the decode thread mutates
        the dict: list() snapshots atomically under the GIL."""
        return [ent.key for ent in list(self._by_key.values())
                if ent.length & (ent.length - 1) == 0]

    def stats(self) -> Dict[str, float]:
        return {
            "entries": len(self._by_key),
            "pinned_pages": self.pinned_pages,
            "queries": self.queries,
            "hits": self.hits,
            "hit_rate": round(self.hits / self.queries, 4)
            if self.queries else 0.0,
            "prefill_tokens_saved": self.tokens_matched,
            "inserts": self.inserts,
            "evictions": self.evictions,
        }
