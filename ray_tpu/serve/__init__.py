"""ray_tpu.serve: model serving on actors (reference: Ray Serve).

Deployments default to the colocated posture (every replica prefills
and decodes). Pass ``role="prefill"`` / ``role="decode"`` to
``serve.deployment`` to split the two phases onto separate fleets with
KV pages handed off over the object plane — see docs/SERVING.md
"Disaggregated prefill/decode"."""

from ray_tpu.serve.api import (  # noqa: F401
    delete,
    get_deployment_handle,
    http_addresses,
    proxy_status,
    run,
    shutdown,
    start_http,
    status,
    stop_http,
    timelines,
)
from ray_tpu.serve.metrics import slo_summary  # noqa: F401
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.decode import (  # noqa: F401
    DecodeEngine,
    LlamaDecodeDeployment,
)
from ray_tpu.serve.build import deploy_config  # noqa: F401
from ray_tpu.serve.prefix_cache import (  # noqa: F401
    PrefixCache,
    candidate_hashes,
    prefix_hash,
)
from ray_tpu.serve.deployment import (  # noqa: F401
    AutoscalingConfig,
    Deployment,
    DeploymentHandle,
    deployment,
)
from ray_tpu.serve.replica import (  # noqa: F401
    get_multiplexed_model_id,
    multiplexed,
    request_deadline_s,
)
from ray_tpu.core.errors import (  # noqa: F401 — request-lifecycle outcomes
    DeadlineExceededError,
    OverloadedError,
    RequestCancelledError,
)
