"""KV-page handoff ledger: the prefill->decode lease of disaggregated
serving.

Disaggregated serving (ROADMAP #3) splits a request across two replica
fleets: a PREFILL replica absorbs the prompt into its paged pool, then
publishes the filled KV pages as object-plane ObjectRefs plus a few
hundred bytes of descriptor; a DECODE replica adopts the pages into its
own pool and streams from the first decode step. Between publish and
adopt the pages live as host-side object-store blobs owned by the
prefill replica's process — this ledger is the accounting for that
window (the serve twin of ``train/pipeline_plane.RefLedger``, which
plays the same role for pipeline activations).

Lease discipline (graftlint ``RESOURCE_METHOD_PAIRS`` polices the
pairing): ``publish_handoff`` registers a descriptor whose refs the
process keeps alive; ``discharge_handoff`` — adopt-ack or abort, either
way — must run on EVERY exception path, directly or through a
self-callee chain. Escape hatches for paths no code can cover:

* prefill replica SIGKILL — the refs' owner process died, so the
  object plane frees the blobs structurally (``_RefTracker`` abandons
  deltas to dead owners); nothing strands.
* router death mid-splice — nobody will discharge, so ``sweep()``
  (driven by the controller's reconcile stats pull, every ~0.25 s)
  expires entries past ``serve_handoff_ttl_s`` and hands their refs
  back to the caller to free. Expiry after a successful adopt is
  harmless: the decode replica already fetched the bytes, and
  freeing a fetched blob just drops storage.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional

# Budget on the serialized DESCRIPTOR (refs + block geometry + first
# token — never the page payload, which rides the object store): the
# router splice forwards it inline with the request, so it must stay
# RPC-header-sized. bench_serve --sections disagg records the observed
# p99 against this.
HANDOFF_DESC_BYTE_BUDGET = 8192


def descriptor_nbytes(desc: Dict[str, Any]) -> int:
    """Serialized size of a handoff descriptor (ObjectRefs reduce to
    (id, owner_addr) — ~100 B each, never the payload)."""
    return len(pickle.dumps(desc, protocol=5))


class HandoffLedger:
    """Per-replica registry of published-but-undischarged handoffs.

    Thread-safe: publish runs on replica request threads, sweep on the
    stats/metrics pull path. Entries are keyed by the descriptor's
    ``handoff_id``; values keep the publish timestamp so discharge can
    report the publish->adopt latency."""

    def __init__(self, ttl_s: Optional[float] = None):
        from ray_tpu.core.config import config as rt_config

        self._ttl_s = (rt_config.serve_handoff_ttl_s
                       if ttl_s is None else float(ttl_s))
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict[str, Any]] = {}

    # ------------------------------------------------------------ lease

    def publish_handoff(self, desc: Dict[str, Any]) -> Dict[str, Any]:
        """Register a published handoff; the caller owns discharging it
        (adopt-ack or abort) on every path. Returns ``desc``."""
        with self._lock:
            self._entries[desc["handoff_id"]] = {
                "desc": desc, "t_publish": time.monotonic()}
        return desc

    def discharge_handoff(self, handoff_id: str
                          ) -> Optional[Dict[str, Any]]:
        """Pop a published entry (adopt-ack, abort, or expiry all land
        here). Returns ``{"desc", "age_s"}`` or None when the entry was
        already discharged — discharge is idempotent by design: the
        router's abort path and the TTL sweep may race, and both sides
        freeing is a double-free only the ledger can referee."""
        with self._lock:
            entry = self._entries.pop(handoff_id, None)
        if entry is None:
            return None
        return {"desc": entry["desc"],
                "age_s": time.monotonic() - entry["t_publish"]}

    # ------------------------------------------------------------ sweep

    def sweep(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Pop entries older than the TTL and return them (desc +
        age_s); the caller frees their refs and counts them expired.
        Rides the replica stats pull, so the controller's reconcile
        loop doubles as the returns-the-pages backstop."""
        now = time.monotonic() if now is None else now
        expired: List[Dict[str, Any]] = []
        with self._lock:
            for hid in [h for h, e in self._entries.items()
                        if now - e["t_publish"] > self._ttl_s]:
                entry = self._entries.pop(hid)
                expired.append({"desc": entry["desc"],
                                "age_s": now - entry["t_publish"]})
        return expired

    # ------------------------------------------------------------ stats

    def live(self) -> int:
        with self._lock:
            return len(self._entries)

    def live_bytes(self) -> int:
        """Payload bytes pinned by undischarged handoffs (the number
        that says whether the prefill fleet is leaking)."""
        with self._lock:
            return sum(int(e["desc"].get("nbytes", 0))
                       for e in self._entries.values())
