"""Continuous-batching decode engine: the replica-side half of LLM serving.

Reference shape: the reference serves generation through its model-agnostic
replica call path + streaming (``serve/_private/replica.py:231``,
``proxy.py:761``) and leaves batching to vLLM-style engines; here the
engine is TPU-native and owns the jitted programs directly:

* ONE decode program per (slots, capacity) bucket, compiled once. Requests
  join and leave the running batch between decode steps (continuous
  batching) — a joining request's prompt is prefetched into its slot by a
  single-row prefill program, then the shared ``decode_step`` advances
  every active slot together.
* Static shapes throughout: slot count and cache capacity are fixed at
  engine construction (pick the bucket for your SLO); per-slot ``length``
  masking makes ragged occupancy exact, so there are NO recompiles at
  steady state — the serving property that matters on TPU.
* Streaming: each emitted token is pushed to the request's callback;
  ``serve``'s streaming HTTP path turns that into chunked responses.

Single-threaded by design: the engine runs inside one replica actor
(``max_concurrency`` keeps request intake concurrent; the decode loop is
the serial consumer), matching how a chip is actually scheduled.
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.core.errors import (DeadlineExceededError, OverloadedError,
                                 RequestCancelledError)

logger = logging.getLogger(__name__)

_req_ids = itertools.count(1)


@dataclass
class _Request:
    tokens: np.ndarray                     # prompt ids, (S,)
    max_new_tokens: int
    temperature: float
    eos_id: Optional[int]
    on_token: Optional[Callable[[int], None]]
    done: threading.Event = field(default_factory=threading.Event)
    output: List[int] = field(default_factory=list)
    slot: int = -1
    generated: int = 0
    error: Optional[str] = None
    on_token_error: Optional[str] = None   # first on_token callback failure
    submitted_at: float = field(default_factory=time.monotonic)
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    prefix_entry: int = -1                 # prefix-pool row spliced in
    prefix_len: int = 0                    # cached tokens NOT re-prefilled
    # --------------------------------------------------- request lifecycle
    request_id: str = ""
    deadline: Optional[float] = None       # absolute monotonic; None = none
    cancelled: bool = False                # cooperative-cancel flag
    admitted: bool = False                 # left the pending queue
    status: str = "pending"                # terminal: completed |
    #   cancelled | deadline_exceeded | error

    def raise_for_status(self) -> None:
        """Re-raise this request's terminal outcome as its typed error."""
        if self.status == "cancelled":
            raise RequestCancelledError(
                f"request {self.request_id} cancelled after "
                f"{self.generated} tokens")
        if self.status == "deadline_exceeded":
            raise DeadlineExceededError(
                f"request {self.request_id} exceeded its deadline after "
                f"{self.generated} tokens")
        if self.error:
            raise RuntimeError(self.error)


class DecodeEngine:
    """Continuous batcher over ``llama_decode`` programs.

    ``slots`` concurrent sequences share one KV cache of ``capacity``
    tokens per slot. ``step()`` advances every active slot one token;
    ``submit()`` enqueues a request (prefilled into a free slot at the
    next step boundary). Run ``serve_forever`` in a thread inside a
    replica, or drive ``step()`` manually in tests."""

    def __init__(self, params, config, slots: int = 4,
                 capacity: int = 1024, prefill_bucket: int = 128,
                 decode_chunk: int = 1,
                 prefix_pool_entries: Optional[int] = None,
                 prefix_capacity: Optional[int] = None,
                 prefix_match_min_tokens: Optional[int] = None,
                 queue_max: Optional[int] = None):
        import jax

        from ray_tpu.core.config import config as rt_config
        from ray_tpu.models import llama_decode as ld
        from ray_tpu.serve.prefix_cache import PrefixCache

        self._jax = jax
        self._ld = ld
        self.params = params
        self.config = config
        self.slots = slots
        self.capacity = capacity
        self.prefill_bucket = prefill_bucket
        self.cache = ld.init_cache(config, slots, capacity)
        self._free = list(range(slots))
        self._active: Dict[int, _Request] = {}
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._tokens = np.zeros((slots,), np.int32)
        self._rng = np.random.default_rng(0)
        self._stop = threading.Event()
        self._work = threading.Event()
        # ------------------------------------------- request lifecycle
        # Bounded admission: past queue_max pending requests, submit()
        # sheds with OverloadedError at enqueue (<1 ms) instead of
        # queueing into minutes of latency under overload.
        if queue_max is None:
            queue_max = rt_config.decode_queue_max
        self.queue_max = int(queue_max) if queue_max else slots * 8
        # request_id -> live request, for cancel(); guarded by _reqs_lock
        # (intake/cancel threads vs the decode loop).
        self._requests: Dict[str, _Request] = {}
        self._reqs_lock = threading.Lock()
        self._queued_cancelled = 0  # cancelled but not yet dequeued
        self.shed = 0               # requests rejected by the queue cap
        self.cancelled = 0          # requests ended by cancel()
        self.deadline_exceeded = 0  # requests ended by their deadline
        self._ema_request_s = 0.0   # EMA of admitted-request service time
        self._last_purge = 0.0      # dead-entry queue-purge throttle
        # Prefix KV cache: a device-resident pool of cached prompt-prefix
        # K/V (P entries x C_prefix tokens) indexed by a host-side trie.
        # At admission the longest cached prefix is spliced into the
        # request's slot and only the suffix is prefilled.
        entries = (rt_config.prefix_pool_entries
                   if prefix_pool_entries is None else prefix_pool_entries)
        min_tokens = (rt_config.prefix_match_min_tokens
                      if prefix_match_min_tokens is None
                      else prefix_match_min_tokens)
        if prefix_capacity is None:
            prefix_capacity = 1
            while prefix_capacity * 2 <= capacity // 2:
                prefix_capacity *= 2
        self.prefix: Optional[PrefixCache] = None
        self._pool = None
        if entries > 0 and prefix_capacity >= max(2, min_tokens):
            self.prefix = PrefixCache(entries, prefix_capacity,
                                      min_tokens=min_tokens)
            c = config
            pool_shape = (c.n_layers, entries, prefix_capacity,
                          c.n_kv_heads, c.head_dim)
            import jax.numpy as jnp
            self._pool = {"k": jnp.zeros(pool_shape, c.dtype),
                          "v": jnp.zeros(pool_shape, c.dtype)}
        # Suffix prefills bucket on a finer grid than full prefills: the
        # whole point is that the suffix is short, so padding it back up
        # to prefill_bucket would refund most of the win.
        self._suffix_bucket_min = max(8, min(16, prefill_bucket))
        # Per-(bucket) jitted single-slot prefill: writes one row of the
        # shared cache. Donating the cache makes the slot insert in-place.
        # Params are ARGUMENTS (not closure captures), or jit would bake
        # the weights into the program as constants.
        self._prefill_many = jax.jit(
            self._prefill_many_impl, static_argnames=("n", "bucket"),
            donate_argnums=(1,))
        # Prefix-hit admission: splice pool entries into the wave's slots
        # and prefill only the suffixes — one program per (n, bucket)
        # power-of-two pair, like _prefill_many. Pool insert copies a
        # freshly prefilled slot's leading positions into a pool row.
        self._prefill_suffix_many = jax.jit(
            self._prefill_suffix_many_impl,
            static_argnames=("n", "bucket"), donate_argnums=(1,))
        self._pool_insert = jax.jit(self._pool_insert_impl,
                                    donate_argnums=(1, 2))
        self._decode = jax.jit(self._decode_impl, donate_argnums=(1,))
        # K greedy steps per device call (dispatch amortization); chunking
        # only engages when no admissions are pending and every active
        # request is greedy — sampling and joins stay per-token exact.
        self.decode_chunk = max(1, int(decode_chunk))
        self._decode_k = jax.jit(self._decode_chunk_impl,
                                 static_argnames=("k",),
                                 donate_argnums=(1,))
        self.steps = 0
        self.tokens_out = 0

    # ------------------------------------------------------ jitted bodies

    def _prefill_many_impl(self, params, cache, tokens_rows, lengths,
                           slot_ids, n, bucket):
        """Batched admission: prefill ``n`` rows in ONE device call and
        scatter their K/V into the shared cache at ``slot_ids``. One
        compiled program per (n, bucket) power-of-two pair — dispatch
        overhead amortizes over the whole admission wave."""
        ld, cfg = self._ld, self.config
        batch = ld.init_cache(cfg, n, self.capacity)
        logits, batch = ld.prefill(params, tokens_rows[:, :bucket],
                                   batch, cfg, lengths=lengths)
        s = batch["k"].shape[2]
        new = {
            "k": cache["k"].at[:, slot_ids, :s].set(batch["k"]),
            "v": cache["v"].at[:, slot_ids, :s].set(batch["v"]),
            "length": cache["length"].at[slot_ids].set(lengths),
        }
        return logits, new

    def _prefill_suffix_many_impl(self, params, cache, pool_k, pool_v,
                                  entry_ids, slot_ids, suffix_rows,
                                  prefix_lens, lengths, n, bucket):
        """Prefix-hit admission in ONE device call: gather the wave's
        slot rows, splice the matched pool entries over their leading
        ``C_prefix`` positions, suffix-prefill from ``pos=prefix_lens``,
        and scatter the rows back. The splice copies the WHOLE entry
        region unconditionally (static shape): positions past the match
        are overwritten by the suffix or causally masked, never read."""
        ld = self._ld
        cp = pool_k.shape[2]
        # Every read/write in this program lands below prefix+suffix
        # (prefix_lens <= C_prefix, suffix spans `bucket`), so the
        # gather, attention, and scatter run over that STATIC bound
        # instead of the full capacity — the suffix path's cost scales
        # with what it touches, not with the engine's max context.
        lim = min(self.capacity, cp + bucket)
        rows_k = cache["k"][:, slot_ids, :lim]    # (L, n, lim, KV, D)
        rows_v = cache["v"][:, slot_ids, :lim]
        rows_k = rows_k.at[:, :, :cp].set(pool_k[:, entry_ids])
        rows_v = rows_v.at[:, :, :cp].set(pool_v[:, entry_ids])
        row_cache = {"k": rows_k, "v": rows_v, "length": lengths}
        logits, row_cache = ld.prefill_suffix(
            params, suffix_rows[:, :bucket], row_cache, self.config,
            prefix_lens, lengths)
        new = {
            "k": cache["k"].at[:, slot_ids, :lim].set(row_cache["k"]),
            "v": cache["v"].at[:, slot_ids, :lim].set(row_cache["v"]),
            "length": cache["length"].at[slot_ids].set(lengths),
        }
        return logits, new

    def _pool_insert_impl(self, cache, pool_k, pool_v, slot, entry):
        cp = pool_k.shape[2]
        new_k = pool_k.at[:, entry].set(cache["k"][:, slot, :cp])
        new_v = pool_v.at[:, entry].set(cache["v"][:, slot, :cp])
        return new_k, new_v

    def _decode_impl(self, params, cache, tokens):
        return self._ld.decode_step(params, cache, tokens, self.config)

    def _decode_chunk_impl(self, params, cache, tokens, k):
        return self._ld.decode_chunk(params, cache, tokens, self.config,
                                     k)

    # ------------------------------------------------------------ intake

    def submit(self, prompt_tokens, max_new_tokens: int = 32,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               on_token: Optional[Callable[[int], None]] = None,
               deadline_s: Optional[float] = None,
               request_id: Optional[str] = None) -> _Request:
        req = _Request(np.asarray(prompt_tokens, np.int32).reshape(-1),
                       int(max_new_tokens), float(temperature), eos_id,
                       on_token)
        req.request_id = request_id or f"req-{next(_req_ids)}"
        if len(req.tokens) >= self.capacity:
            raise ValueError(
                f"prompt ({len(req.tokens)}) must be shorter than the "
                f"cache capacity ({self.capacity})")
        if len(req.tokens) + req.max_new_tokens > self.capacity:
            # Past capacity the K/V scatter at pos=length goes out of
            # bounds and JAX silently drops it — the request would return
            # wrong tokens, not an error. generate() sizes its cache as
            # cache_bucket(S + max_new_tokens); the engine's cache is
            # fixed, so the same budget must hold at admission.
            raise ValueError(
                f"prompt ({len(req.tokens)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds the cache capacity "
                f"({self.capacity})")
        if deadline_s is not None:
            if deadline_s <= 0:
                self.deadline_exceeded += 1
                raise DeadlineExceededError(
                    f"request {req.request_id} arrived with an already-"
                    f"expired deadline ({deadline_s:.3f}s)")
            req.deadline = time.monotonic() + float(deadline_s)
        # Load shedding happens HERE, at enqueue — not after minutes in
        # queue. qsize() can transiently overshoot by concurrent
        # submitters, but the check bounds the queue within one wave.
        if self._pending.qsize() - self._queued_cancelled >= self.queue_max:
            self.shed += 1
            raise OverloadedError(
                f"decode queue at capacity ({self.queue_max} pending, "
                f"{self.slots} slots)",
                retry_after_s=self.retry_after_estimate_s())
        with self._reqs_lock:
            self._requests[req.request_id] = req
        self._pending.put(req)
        self._work.set()
        return req

    def retry_after_estimate_s(self) -> float:
        """How long a shed caller should wait before retrying, from the
        observed per-request service time: the queue drains ``slots``
        requests per service interval, so a rejected request's turn is
        about ``(queued / slots + 1)`` intervals away. Clamped to
        [0.5 s, 30 s]; 1 s before any request has completed."""
        if self._ema_request_s <= 0:
            return 1.0
        depth = max(0, self._pending.qsize() - self._queued_cancelled)
        est = (depth / max(1, self.slots) + 1.0) * self._ema_request_s
        return min(30.0, max(0.5, est))

    def cancel(self, request_id: str) -> bool:
        """Cooperative cancellation: mark the request; the decode loop
        drops it before prefill if still queued, or frees its slot at the
        next ``step()`` boundary if active. Returns False for unknown /
        already-finished requests (cancel is idempotent)."""
        with self._reqs_lock:
            req = self._requests.get(request_id)
            if req is None or req.done.is_set() or req.cancelled:
                return False
            req.cancelled = True
            if not req.admitted:
                # Still in the pending queue: exclude it from the load
                # signal now; _admit reconciles when it dequeues it.
                self._queued_cancelled += 1
        self._work.set()  # wake a parked loop so the drop is prompt
        return True

    # -------------------------------------------------------- the loop

    def _admit(self) -> None:
        while self._free and not self._pending.empty():
            # Drain up to len(free) pending requests, split them into
            # prefix-cache hits and misses, and prefill each group as
            # ONE batched device call per prompt/suffix bucket.
            wave: List[_Request] = []
            while len(wave) < len(self._free):
                try:
                    wave.append(self._pending.get_nowait())
                except queue.Empty:
                    break
            if not wave:
                return
            # Dead-on-arrival requests (cancelled while queued, or
            # deadline already passed) retire HERE — before any prefix
            # match or device work. They never touch the device and the
            # wave refills from the queue behind them.
            live: List[_Request] = []
            now = time.monotonic()
            for req in wave:
                with self._reqs_lock:
                    req.admitted = True
                    if req.cancelled:
                        self._queued_cancelled -= 1
                if req.cancelled:
                    self._retire(req, "cancelled")
                elif req.deadline is not None and now > req.deadline:
                    self._retire(req, "deadline_exceeded")
                else:
                    live.append(req)
            if not live:
                continue
            hits: List[_Request] = []
            misses: List[_Request] = []
            for req in live:
                m = (self.prefix.match(req.tokens)
                     if self.prefix is not None else None)
                if m is not None:
                    req.prefix_entry, req.prefix_len = m
                    hits.append(req)
                else:
                    misses.append(req)
            self._admit_full(misses)
            self._admit_suffix(hits)

    def _retire(self, req: _Request, status: str) -> None:
        """Terminal exit for a request that never held a slot."""
        req.status = status
        req.finished_at = time.monotonic()
        if status == "cancelled":
            self.cancelled += 1
        elif status == "deadline_exceeded":
            self.deadline_exceeded += 1
        with self._reqs_lock:
            self._requests.pop(req.request_id, None)
        req.done.set()

    def _purge_pending(self) -> None:
        """Drop dead entries (cancelled / deadline-expired) from the
        pending queue WITHOUT waiting for a slot to free: when every
        slot is busy for minutes, admission never runs, but a cancelled
        caller's entry must still retire promptly — it would otherwise
        hold its done-event, its _requests entry, and (for expiries)
        inflate the load signal. One FIFO-preserving rotation."""
        now = time.monotonic()
        for _ in range(self._pending.qsize()):
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            with self._reqs_lock:
                dead = req.cancelled
                if dead:
                    self._queued_cancelled -= 1
                    req.admitted = True
            if dead:
                self._retire(req, "cancelled")
            elif req.deadline is not None and now > req.deadline:
                with self._reqs_lock:
                    req.admitted = True
                self._retire(req, "deadline_exceeded")
            else:
                self._pending.put(req)

    def _admit_full(self, reqs: List[_Request]) -> None:
        import jax.numpy as jnp

        ld = self._ld
        by_bucket: Dict[int, List[_Request]] = {}
        for req in reqs:
            bucket = min(ld.cache_bucket(len(req.tokens),
                                         self.prefill_bucket),
                         self.capacity)
            by_bucket.setdefault(bucket, []).append(req)
        for bucket, group in by_bucket.items():
            slots = [self._free.pop() for _ in group]
            # Pad the admission count to a power of two (bounded
            # program set); pad rows REPEAT the last real row into
            # the same slot — an idempotent overwrite.
            n = 1
            while n < len(group):
                n *= 2
            rows = np.zeros((n, bucket), np.int32)
            lengths = np.zeros((n,), np.int32)
            slot_ids = np.full((n,), slots[-1], np.int32)
            for i, req in enumerate(group):
                rows[i, :len(req.tokens)] = req.tokens
                lengths[i] = len(req.tokens)
                slot_ids[i] = slots[i]
            for i in range(len(group), n):  # idempotent pad rows
                rows[i] = rows[len(group) - 1]
                lengths[i] = lengths[len(group) - 1]
            logits, self.cache = self._prefill_many(
                self.params, self.cache, jnp.asarray(rows),
                jnp.asarray(lengths), jnp.asarray(slot_ids),
                n=n, bucket=bucket)
            self._post_admit(group, slots, np.asarray(logits))

    def _admit_suffix(self, reqs: List[_Request]) -> None:
        """Prefix-hit admissions: splice the matched pool entry into each
        request's slot and prefill only the uncached suffix."""
        import jax.numpy as jnp

        ld = self._ld
        by_bucket: Dict[int, List[_Request]] = {}
        for req in reqs:
            suffix_len = len(req.tokens) - req.prefix_len
            bucket = min(ld.cache_bucket(suffix_len,
                                         self._suffix_bucket_min),
                         self.capacity)
            by_bucket.setdefault(bucket, []).append(req)
        for bucket, group in by_bucket.items():
            slots = [self._free.pop() for _ in group]
            n = 1
            while n < len(group):
                n *= 2
            rows = np.zeros((n, bucket), np.int32)
            plens = np.zeros((n,), np.int32)
            lengths = np.zeros((n,), np.int32)
            entries = np.zeros((n,), np.int32)
            slot_ids = np.full((n,), slots[-1], np.int32)
            for i, req in enumerate(group):
                suffix = req.tokens[req.prefix_len:]
                rows[i, :len(suffix)] = suffix
                plens[i] = req.prefix_len
                lengths[i] = len(req.tokens)
                entries[i] = req.prefix_entry
                slot_ids[i] = slots[i]
            for i in range(len(group), n):  # idempotent pad rows
                rows[i] = rows[len(group) - 1]
                plens[i] = plens[len(group) - 1]
                lengths[i] = lengths[len(group) - 1]
                entries[i] = entries[len(group) - 1]
            logits, self.cache = self._prefill_suffix_many(
                self.params, self.cache, self._pool["k"], self._pool["v"],
                jnp.asarray(entries), jnp.asarray(slot_ids),
                jnp.asarray(rows), jnp.asarray(plens),
                jnp.asarray(lengths), n=n, bucket=bucket)
            for req in group:
                # The splice program holding the entry is dispatched (and
                # device order is program order), so the row may now be
                # recycled without racing the read.
                self.prefix.release(req.prefix_entry)
            self._post_admit(group, slots, np.asarray(logits))

    def _post_admit(self, group: List[_Request], slots: List[int],
                    logits: np.ndarray) -> None:
        now = time.monotonic()
        for i, req in enumerate(group):
            tok = self._sample_host(logits[i], req)
            req.slot = slots[i]
            req.first_token_at = now
            self._emit(req, tok)
            self._tokens[slots[i]] = tok
            self._active[slots[i]] = req
            if req.generated >= req.max_new_tokens or (
                    req.eos_id is not None and tok == req.eos_id):
                self._finish(slots[i])
        # Insert the freshly prefilled prompts back into the prefix pool
        # NOW, before any later admission can recycle these slots: the
        # slot rows still hold the full prompt K/V (a _finish only parks
        # ``length``), and pool inserts dedup on the token key.
        if self.prefix is not None:
            for req, slot in zip(group, slots):
                ins = self.prefix.insert(req.tokens,
                                         matched_len=req.prefix_len)
                if ins is not None:
                    row, _ins_len = ins
                    self._pool["k"], self._pool["v"] = self._pool_insert(
                        self.cache, self._pool["k"], self._pool["v"],
                        slot, row)

    def _sample_host(self, logits: np.ndarray, req: _Request) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits / req.temperature
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    _last_cb_log = 0.0  # class-wide rate limit for callback-failure logs

    def _emit(self, req: _Request, tok: int) -> None:
        req.output.append(tok)
        req.generated += 1
        self.tokens_out += 1
        if req.on_token is None:
            return
        try:
            req.on_token(tok)
        except Exception as e:  # noqa: BLE001 — the decode loop must
            # survive a broken streaming consumer, but silently eating
            # the error made streaming failures undiagnosable. Record
            # the FIRST failure on the request and log once per request
            # (rate-limited across requests: a wedged consumer fails on
            # every token of every request).
            if req.on_token_error is None:
                req.on_token_error = f"{type(e).__name__}: {e}"
                now = time.monotonic()
                if now - DecodeEngine._last_cb_log > 1.0:
                    DecodeEngine._last_cb_log = now
                    logger.warning(
                        "on_token callback failed (slot %d, %d tokens "
                        "emitted): %s", req.slot, req.generated,
                        req.on_token_error, exc_info=True)

    def _finish(self, slot: int, status: str = "completed") -> None:
        req = self._active.pop(slot)
        # Return the slot IMMEDIATELY after the active-pop: _free is only
        # consumed by _admit on this same thread, but stats() reads both
        # cross-thread — a device dispatch between the pop and the append
        # would show active+free < slots (a phantom wedged slot).
        self._free.append(slot)
        req.status = status
        req.finished_at = time.monotonic()
        if status == "completed":
            # Service-time EMA feeds the shed path's Retry-After estimate.
            service = req.finished_at - req.submitted_at
            self._ema_request_s = (service if self._ema_request_s <= 0
                                   else 0.7 * self._ema_request_s
                                   + 0.3 * service)
        elif status == "cancelled":
            self.cancelled += 1
        elif status == "deadline_exceeded":
            self.deadline_exceeded += 1
        with self._reqs_lock:
            self._requests.pop(req.request_id, None)
        req.done.set()
        # Park the freed slot at length 0 so idle slots don't walk their
        # cursor toward the capacity edge while others decode.
        self.cache["length"] = self.cache["length"].at[slot].set(0)
        self._tokens[slot] = 0

    def _reap(self) -> None:
        """Free slots whose requests are dead (cancelled, or past their
        deadline): runs at every step boundary, so a dead request costs
        at most ONE more decode step — its slot and its place in the
        batch go back to live traffic immediately (the property Orca-
        style iteration-level scheduling is for)."""
        now = time.monotonic()
        if (self._queued_cancelled > 0
                or (now - self._last_purge > 0.5
                    and not self._pending.empty())):
            self._last_purge = now
            self._purge_pending()
        for slot in list(self._active):
            req = self._active[slot]
            if req.cancelled:
                self._finish(slot, "cancelled")
            elif req.deadline is not None and now > req.deadline:
                self._finish(slot, "deadline_exceeded")

    def step(self) -> int:
        """Admit pending prefills, advance every active slot one token.
        Returns the number of active slots stepped."""
        import jax.numpy as jnp

        self._reap()
        self._admit()
        if not self._active:
            return 0
        stepped = len(self._active)
        chunk = 1
        # Chunking engages when the batch can't change mid-chunk anyway
        # (no free slot for a pending request) or nothing is waiting.
        if (self.decode_chunk > 1
                and (self._pending.empty() or not self._free)
                and all(r.temperature <= 0.0
                        for r in self._active.values())):
            chunk = min(self.decode_chunk,
                        min(r.max_new_tokens - r.generated
                            for r in self._active.values()))
            # Round down to a power of two: each distinct k is its own
            # compiled program, so the program set must stay bounded
            # ({1, 2, 4, ..., decode_chunk}), not one per remaining-count.
            while chunk & (chunk - 1):
                chunk &= chunk - 1
        if chunk > 1:
            toks, self.cache = self._decode_k(
                self.params, self.cache, jnp.asarray(self._tokens),
                k=chunk)
            toks = np.asarray(toks)  # (chunk, slots)
            self.steps += chunk
            for slot in list(self._active):
                req = self._active[slot]
                for i in range(chunk):
                    tok = int(toks[i, slot])
                    self._emit(req, tok)
                    self._tokens[slot] = tok
                    if req.generated >= req.max_new_tokens or (
                            req.eos_id is not None
                            and tok == req.eos_id):
                        self._finish(slot)
                        break
            return stepped
        logits, self.cache = self._decode(
            self.params, self.cache, jnp.asarray(self._tokens))
        logits = np.asarray(logits)
        self.steps += 1
        for slot in list(self._active):
            req = self._active[slot]
            tok = self._sample_host(logits[slot], req)
            self._emit(req, tok)
            self._tokens[slot] = tok
            if req.generated >= req.max_new_tokens or (
                    req.eos_id is not None and tok == req.eos_id):
                self._finish(slot)
        return stepped

    def serve_forever(self, idle_wait_s: float = 0.05) -> None:
        """Decode loop for a replica thread: steps while work exists,
        parks on an event while idle."""
        while not self._stop.is_set():
            if self._active or not self._pending.empty():
                self.step()
            else:
                self._work.clear()
                self._work.wait(timeout=idle_wait_s)

    def shutdown(self) -> None:
        self._stop.set()
        self._work.set()

    # ------------------------------------------------------------ stats

    def stats(self) -> Dict[str, Any]:
        active = len(self._active)
        # Live queue depth: cancelled-but-undequeued entries are dead
        # weight, not demand — the autoscaler must not scale out for
        # requests that will be dropped at admission.
        queued = max(0, self._pending.qsize() - self._queued_cancelled)
        out = {
            "steps": self.steps,
            "tokens_out": self.tokens_out,
            "active": active,
            "slots": self.slots,
            "free_slots": len(self._free),
            "queued": queued,
            "queue_max": self.queue_max,
            # Degradation counters: shed-at-enqueue, cooperative
            # cancellations, and deadline expiries — surfaced through
            # replica_metrics -> controller snapshot -> serve.status()
            # so overload shows up as it happens.
            "shed": self.shed,
            "cancelled": self.cancelled,
            "deadline_exceeded": self.deadline_exceeded,
            # Decode backlog as replica load: occupied slots + pending
            # queue depth. A full queue behind idle HTTP must read as
            # load to the serve autoscaler, not zero.
            "load": active + queued,
        }
        if self.prefix is not None:
            out["prefix"] = self.prefix.stats()
        return out


class LlamaDecodeDeployment:
    """Serve deployment wrapping a DecodeEngine: POST {"tokens": [...],
    "max_new_tokens": N} -> {"tokens": [...]} with streaming support
    (generator handle path). Replica-per-chip: schedule with
    ``ray_actor_options={"resources": {"TPU": 1}}``."""

    def __init__(self, preset: str = "debug", slots: int = 4,
                 capacity: int = 1024, seed: int = 0,
                 config=None, decode_chunk: int = 1,
                 prefix_pool_entries: Optional[int] = None,
                 prefix_capacity: Optional[int] = None,
                 prefix_match_min_tokens: Optional[int] = None,
                 queue_max: Optional[int] = None):
        import jax

        from ray_tpu.models import llama

        cfg = config or llama.PRESETS[preset]
        self.cfg = cfg
        params = llama.init_params(cfg, jax.random.key(seed))
        self.engine = DecodeEngine(
            params, cfg, slots=slots, capacity=capacity,
            decode_chunk=decode_chunk,
            prefix_pool_entries=prefix_pool_entries,
            prefix_capacity=prefix_capacity,
            prefix_match_min_tokens=prefix_match_min_tokens,
            queue_max=queue_max)
        self._thread = threading.Thread(target=self.engine.serve_forever,
                                        name="decode-loop", daemon=True)
        self._thread.start()

    def replica_metrics(self) -> Dict[str, Any]:
        """Replica-reported load + prefix residency + degradation
        counters, merged into ``ReplicaActor.stats()``: the autoscaler
        scales on decode backlog, the router steers shared prefixes to
        the replica already holding them, and ``serve.status()`` shows
        shedding/cancellation/deadline counts as they happen."""
        s = self.engine.stats()
        out: Dict[str, Any] = {"load": s["load"], "queued": s["queued"],
                               "shed": s["shed"],
                               "cancelled": s["cancelled"],
                               "deadline_exceeded": s["deadline_exceeded"]}
        if self.engine.prefix is not None:
            out["prefix"] = s.get("prefix", {})
            out["prefixes"] = self.engine.prefix.hashes()
        return out

    def _submit(self, request: Dict[str, Any], on_token=None) -> _Request:
        """Admission with the request's deadline attached: explicit
        ``deadline_s`` in the payload wins, else the deadline the serve
        stack propagated with this call (proxy header / handle
        timeout_s / ``serve_request_timeout_s``)."""
        from ray_tpu.serve.replica import request_deadline_s

        deadline_s = request.get("deadline_s")
        if deadline_s is None:
            deadline_s = request_deadline_s()
        return self.engine.submit(
            request["tokens"],
            max_new_tokens=int(request.get("max_new_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"),
            on_token=on_token,
            deadline_s=deadline_s,
            request_id=request.get("request_id"))

    def __call__(self, request: Dict[str, Any]):
        if request.get("stream"):
            # Generator return = the replica streams it (handle.stream /
            # HTTP chunked via X-Serve-Stream on this same route).
            return self.stream(request)
        req = self._submit(request)
        if req.deadline is not None:
            # The engine enforces the deadline; the +10 s slack only
            # covers a wedged decode loop (never-completing wait).
            if not req.done.wait(
                    max(0.1, req.deadline - time.monotonic()) + 10.0):
                self.engine.cancel(req.request_id)
                raise DeadlineExceededError(
                    f"request {req.request_id} not finished by the decode "
                    f"loop within its deadline")
        else:
            req.done.wait()
        req.raise_for_status()
        return {"tokens": req.output,
                "ttft_s": round(req.first_token_at - req.submitted_at, 4)}

    def stream(self, request: Dict[str, Any]):
        """Streaming generator: yields tokens as the engine emits them
        (drive via a streaming handle / HTTP chunked response). Closing
        the generator (client disconnect anywhere up the stack) cancels
        the engine request: the slot frees at the next step and queued-
        but-unadmitted requests never touch the device."""
        q: "queue.Queue" = queue.Queue()
        req = self._submit(request, on_token=q.put)
        try:
            while True:
                try:
                    yield q.get(timeout=0.5)
                    continue
                except queue.Empty:
                    pass
                if req.done.is_set():
                    while not q.empty():
                        yield q.get()
                    # A mid-stream deadline/cancel surfaces as the typed
                    # error instead of silently truncating the stream.
                    req.raise_for_status()
                    break
        finally:
            if not req.done.is_set():
                self.engine.cancel(req.request_id)

    def health(self) -> Dict[str, Any]:
        return self.engine.stats()
