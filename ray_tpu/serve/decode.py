"""Continuous-batching decode engine: the replica-side half of LLM serving.

Reference shape: the reference serves generation through its model-agnostic
replica call path + streaming (``serve/_private/replica.py:231``,
``proxy.py:761``) and leaves batching to vLLM-style engines; here the
engine is TPU-native and owns the jitted programs directly:

* ONE decode program per (slots, capacity) bucket, compiled once. Requests
  join and leave the running batch between decode steps (continuous
  batching) — a joining request's prompt is prefetched into its slot by a
  single-row prefill program, then the shared ``decode_step`` advances
  every active slot together.
* Static shapes throughout: slot count and cache capacity are fixed at
  engine construction (pick the bucket for your SLO); per-slot ``length``
  masking makes ragged occupancy exact, so there are NO recompiles at
  steady state — the serving property that matters on TPU.
* Streaming: each emitted token is pushed to the request's callback;
  ``serve``'s streaming HTTP path turns that into chunked responses.

Single-threaded by design: the engine runs inside one replica actor
(``max_concurrency`` keeps request intake concurrent; the decode loop is
the serial consumer), matching how a chip is actually scheduled.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)


@dataclass
class _Request:
    tokens: np.ndarray                     # prompt ids, (S,)
    max_new_tokens: int
    temperature: float
    eos_id: Optional[int]
    on_token: Optional[Callable[[int], None]]
    done: threading.Event = field(default_factory=threading.Event)
    output: List[int] = field(default_factory=list)
    slot: int = -1
    generated: int = 0
    error: Optional[str] = None
    on_token_error: Optional[str] = None   # first on_token callback failure
    submitted_at: float = field(default_factory=time.monotonic)
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    prefix_entry: int = -1                 # prefix-pool row spliced in
    prefix_len: int = 0                    # cached tokens NOT re-prefilled


class DecodeEngine:
    """Continuous batcher over ``llama_decode`` programs.

    ``slots`` concurrent sequences share one KV cache of ``capacity``
    tokens per slot. ``step()`` advances every active slot one token;
    ``submit()`` enqueues a request (prefilled into a free slot at the
    next step boundary). Run ``serve_forever`` in a thread inside a
    replica, or drive ``step()`` manually in tests."""

    def __init__(self, params, config, slots: int = 4,
                 capacity: int = 1024, prefill_bucket: int = 128,
                 decode_chunk: int = 1,
                 prefix_pool_entries: Optional[int] = None,
                 prefix_capacity: Optional[int] = None,
                 prefix_match_min_tokens: Optional[int] = None):
        import jax

        from ray_tpu.core.config import config as rt_config
        from ray_tpu.models import llama_decode as ld
        from ray_tpu.serve.prefix_cache import PrefixCache

        self._jax = jax
        self._ld = ld
        self.params = params
        self.config = config
        self.slots = slots
        self.capacity = capacity
        self.prefill_bucket = prefill_bucket
        self.cache = ld.init_cache(config, slots, capacity)
        self._free = list(range(slots))
        self._active: Dict[int, _Request] = {}
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._tokens = np.zeros((slots,), np.int32)
        self._rng = np.random.default_rng(0)
        self._stop = threading.Event()
        self._work = threading.Event()
        # Prefix KV cache: a device-resident pool of cached prompt-prefix
        # K/V (P entries x C_prefix tokens) indexed by a host-side trie.
        # At admission the longest cached prefix is spliced into the
        # request's slot and only the suffix is prefilled.
        entries = (rt_config.prefix_pool_entries
                   if prefix_pool_entries is None else prefix_pool_entries)
        min_tokens = (rt_config.prefix_match_min_tokens
                      if prefix_match_min_tokens is None
                      else prefix_match_min_tokens)
        if prefix_capacity is None:
            prefix_capacity = 1
            while prefix_capacity * 2 <= capacity // 2:
                prefix_capacity *= 2
        self.prefix: Optional[PrefixCache] = None
        self._pool = None
        if entries > 0 and prefix_capacity >= max(2, min_tokens):
            self.prefix = PrefixCache(entries, prefix_capacity,
                                      min_tokens=min_tokens)
            c = config
            pool_shape = (c.n_layers, entries, prefix_capacity,
                          c.n_kv_heads, c.head_dim)
            import jax.numpy as jnp
            self._pool = {"k": jnp.zeros(pool_shape, c.dtype),
                          "v": jnp.zeros(pool_shape, c.dtype)}
        # Suffix prefills bucket on a finer grid than full prefills: the
        # whole point is that the suffix is short, so padding it back up
        # to prefill_bucket would refund most of the win.
        self._suffix_bucket_min = max(8, min(16, prefill_bucket))
        # Per-(bucket) jitted single-slot prefill: writes one row of the
        # shared cache. Donating the cache makes the slot insert in-place.
        # Params are ARGUMENTS (not closure captures), or jit would bake
        # the weights into the program as constants.
        self._prefill_many = jax.jit(
            self._prefill_many_impl, static_argnames=("n", "bucket"),
            donate_argnums=(1,))
        # Prefix-hit admission: splice pool entries into the wave's slots
        # and prefill only the suffixes — one program per (n, bucket)
        # power-of-two pair, like _prefill_many. Pool insert copies a
        # freshly prefilled slot's leading positions into a pool row.
        self._prefill_suffix_many = jax.jit(
            self._prefill_suffix_many_impl,
            static_argnames=("n", "bucket"), donate_argnums=(1,))
        self._pool_insert = jax.jit(self._pool_insert_impl,
                                    donate_argnums=(1, 2))
        self._decode = jax.jit(self._decode_impl, donate_argnums=(1,))
        # K greedy steps per device call (dispatch amortization); chunking
        # only engages when no admissions are pending and every active
        # request is greedy — sampling and joins stay per-token exact.
        self.decode_chunk = max(1, int(decode_chunk))
        self._decode_k = jax.jit(self._decode_chunk_impl,
                                 static_argnames=("k",),
                                 donate_argnums=(1,))
        self.steps = 0
        self.tokens_out = 0

    # ------------------------------------------------------ jitted bodies

    def _prefill_many_impl(self, params, cache, tokens_rows, lengths,
                           slot_ids, n, bucket):
        """Batched admission: prefill ``n`` rows in ONE device call and
        scatter their K/V into the shared cache at ``slot_ids``. One
        compiled program per (n, bucket) power-of-two pair — dispatch
        overhead amortizes over the whole admission wave."""
        ld, cfg = self._ld, self.config
        batch = ld.init_cache(cfg, n, self.capacity)
        logits, batch = ld.prefill(params, tokens_rows[:, :bucket],
                                   batch, cfg, lengths=lengths)
        s = batch["k"].shape[2]
        new = {
            "k": cache["k"].at[:, slot_ids, :s].set(batch["k"]),
            "v": cache["v"].at[:, slot_ids, :s].set(batch["v"]),
            "length": cache["length"].at[slot_ids].set(lengths),
        }
        return logits, new

    def _prefill_suffix_many_impl(self, params, cache, pool_k, pool_v,
                                  entry_ids, slot_ids, suffix_rows,
                                  prefix_lens, lengths, n, bucket):
        """Prefix-hit admission in ONE device call: gather the wave's
        slot rows, splice the matched pool entries over their leading
        ``C_prefix`` positions, suffix-prefill from ``pos=prefix_lens``,
        and scatter the rows back. The splice copies the WHOLE entry
        region unconditionally (static shape): positions past the match
        are overwritten by the suffix or causally masked, never read."""
        ld = self._ld
        cp = pool_k.shape[2]
        # Every read/write in this program lands below prefix+suffix
        # (prefix_lens <= C_prefix, suffix spans `bucket`), so the
        # gather, attention, and scatter run over that STATIC bound
        # instead of the full capacity — the suffix path's cost scales
        # with what it touches, not with the engine's max context.
        lim = min(self.capacity, cp + bucket)
        rows_k = cache["k"][:, slot_ids, :lim]    # (L, n, lim, KV, D)
        rows_v = cache["v"][:, slot_ids, :lim]
        rows_k = rows_k.at[:, :, :cp].set(pool_k[:, entry_ids])
        rows_v = rows_v.at[:, :, :cp].set(pool_v[:, entry_ids])
        row_cache = {"k": rows_k, "v": rows_v, "length": lengths}
        logits, row_cache = ld.prefill_suffix(
            params, suffix_rows[:, :bucket], row_cache, self.config,
            prefix_lens, lengths)
        new = {
            "k": cache["k"].at[:, slot_ids, :lim].set(row_cache["k"]),
            "v": cache["v"].at[:, slot_ids, :lim].set(row_cache["v"]),
            "length": cache["length"].at[slot_ids].set(lengths),
        }
        return logits, new

    def _pool_insert_impl(self, cache, pool_k, pool_v, slot, entry):
        cp = pool_k.shape[2]
        new_k = pool_k.at[:, entry].set(cache["k"][:, slot, :cp])
        new_v = pool_v.at[:, entry].set(cache["v"][:, slot, :cp])
        return new_k, new_v

    def _decode_impl(self, params, cache, tokens):
        return self._ld.decode_step(params, cache, tokens, self.config)

    def _decode_chunk_impl(self, params, cache, tokens, k):
        return self._ld.decode_chunk(params, cache, tokens, self.config,
                                     k)

    # ------------------------------------------------------------ intake

    def submit(self, prompt_tokens, max_new_tokens: int = 32,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               on_token: Optional[Callable[[int], None]] = None
               ) -> _Request:
        req = _Request(np.asarray(prompt_tokens, np.int32).reshape(-1),
                       int(max_new_tokens), float(temperature), eos_id,
                       on_token)
        if len(req.tokens) >= self.capacity:
            raise ValueError(
                f"prompt ({len(req.tokens)}) must be shorter than the "
                f"cache capacity ({self.capacity})")
        if len(req.tokens) + req.max_new_tokens > self.capacity:
            # Past capacity the K/V scatter at pos=length goes out of
            # bounds and JAX silently drops it — the request would return
            # wrong tokens, not an error. generate() sizes its cache as
            # cache_bucket(S + max_new_tokens); the engine's cache is
            # fixed, so the same budget must hold at admission.
            raise ValueError(
                f"prompt ({len(req.tokens)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds the cache capacity "
                f"({self.capacity})")
        self._pending.put(req)
        self._work.set()
        return req

    # -------------------------------------------------------- the loop

    def _admit(self) -> None:
        while self._free and not self._pending.empty():
            # Drain up to len(free) pending requests, split them into
            # prefix-cache hits and misses, and prefill each group as
            # ONE batched device call per prompt/suffix bucket.
            wave: List[_Request] = []
            while len(wave) < len(self._free):
                try:
                    wave.append(self._pending.get_nowait())
                except queue.Empty:
                    break
            if not wave:
                return
            hits: List[_Request] = []
            misses: List[_Request] = []
            for req in wave:
                m = (self.prefix.match(req.tokens)
                     if self.prefix is not None else None)
                if m is not None:
                    req.prefix_entry, req.prefix_len = m
                    hits.append(req)
                else:
                    misses.append(req)
            self._admit_full(misses)
            self._admit_suffix(hits)

    def _admit_full(self, reqs: List[_Request]) -> None:
        import jax.numpy as jnp

        ld = self._ld
        by_bucket: Dict[int, List[_Request]] = {}
        for req in reqs:
            bucket = min(ld.cache_bucket(len(req.tokens),
                                         self.prefill_bucket),
                         self.capacity)
            by_bucket.setdefault(bucket, []).append(req)
        for bucket, group in by_bucket.items():
            slots = [self._free.pop() for _ in group]
            # Pad the admission count to a power of two (bounded
            # program set); pad rows REPEAT the last real row into
            # the same slot — an idempotent overwrite.
            n = 1
            while n < len(group):
                n *= 2
            rows = np.zeros((n, bucket), np.int32)
            lengths = np.zeros((n,), np.int32)
            slot_ids = np.full((n,), slots[-1], np.int32)
            for i, req in enumerate(group):
                rows[i, :len(req.tokens)] = req.tokens
                lengths[i] = len(req.tokens)
                slot_ids[i] = slots[i]
            for i in range(len(group), n):  # idempotent pad rows
                rows[i] = rows[len(group) - 1]
                lengths[i] = lengths[len(group) - 1]
            logits, self.cache = self._prefill_many(
                self.params, self.cache, jnp.asarray(rows),
                jnp.asarray(lengths), jnp.asarray(slot_ids),
                n=n, bucket=bucket)
            self._post_admit(group, slots, np.asarray(logits))

    def _admit_suffix(self, reqs: List[_Request]) -> None:
        """Prefix-hit admissions: splice the matched pool entry into each
        request's slot and prefill only the uncached suffix."""
        import jax.numpy as jnp

        ld = self._ld
        by_bucket: Dict[int, List[_Request]] = {}
        for req in reqs:
            suffix_len = len(req.tokens) - req.prefix_len
            bucket = min(ld.cache_bucket(suffix_len,
                                         self._suffix_bucket_min),
                         self.capacity)
            by_bucket.setdefault(bucket, []).append(req)
        for bucket, group in by_bucket.items():
            slots = [self._free.pop() for _ in group]
            n = 1
            while n < len(group):
                n *= 2
            rows = np.zeros((n, bucket), np.int32)
            plens = np.zeros((n,), np.int32)
            lengths = np.zeros((n,), np.int32)
            entries = np.zeros((n,), np.int32)
            slot_ids = np.full((n,), slots[-1], np.int32)
            for i, req in enumerate(group):
                suffix = req.tokens[req.prefix_len:]
                rows[i, :len(suffix)] = suffix
                plens[i] = req.prefix_len
                lengths[i] = len(req.tokens)
                entries[i] = req.prefix_entry
                slot_ids[i] = slots[i]
            for i in range(len(group), n):  # idempotent pad rows
                rows[i] = rows[len(group) - 1]
                plens[i] = plens[len(group) - 1]
                lengths[i] = lengths[len(group) - 1]
                entries[i] = entries[len(group) - 1]
            logits, self.cache = self._prefill_suffix_many(
                self.params, self.cache, self._pool["k"], self._pool["v"],
                jnp.asarray(entries), jnp.asarray(slot_ids),
                jnp.asarray(rows), jnp.asarray(plens),
                jnp.asarray(lengths), n=n, bucket=bucket)
            for req in group:
                # The splice program holding the entry is dispatched (and
                # device order is program order), so the row may now be
                # recycled without racing the read.
                self.prefix.release(req.prefix_entry)
            self._post_admit(group, slots, np.asarray(logits))

    def _post_admit(self, group: List[_Request], slots: List[int],
                    logits: np.ndarray) -> None:
        now = time.monotonic()
        for i, req in enumerate(group):
            tok = self._sample_host(logits[i], req)
            req.slot = slots[i]
            req.first_token_at = now
            self._emit(req, tok)
            self._tokens[slots[i]] = tok
            self._active[slots[i]] = req
            if req.generated >= req.max_new_tokens or (
                    req.eos_id is not None and tok == req.eos_id):
                self._finish(slots[i])
        # Insert the freshly prefilled prompts back into the prefix pool
        # NOW, before any later admission can recycle these slots: the
        # slot rows still hold the full prompt K/V (a _finish only parks
        # ``length``), and pool inserts dedup on the token key.
        if self.prefix is not None:
            for req, slot in zip(group, slots):
                ins = self.prefix.insert(req.tokens,
                                         matched_len=req.prefix_len)
                if ins is not None:
                    row, _ins_len = ins
                    self._pool["k"], self._pool["v"] = self._pool_insert(
                        self.cache, self._pool["k"], self._pool["v"],
                        slot, row)

    def _sample_host(self, logits: np.ndarray, req: _Request) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits / req.temperature
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    _last_cb_log = 0.0  # class-wide rate limit for callback-failure logs

    def _emit(self, req: _Request, tok: int) -> None:
        req.output.append(tok)
        req.generated += 1
        self.tokens_out += 1
        if req.on_token is None:
            return
        try:
            req.on_token(tok)
        except Exception as e:  # noqa: BLE001 — the decode loop must
            # survive a broken streaming consumer, but silently eating
            # the error made streaming failures undiagnosable. Record
            # the FIRST failure on the request and log once per request
            # (rate-limited across requests: a wedged consumer fails on
            # every token of every request).
            if req.on_token_error is None:
                req.on_token_error = f"{type(e).__name__}: {e}"
                now = time.monotonic()
                if now - DecodeEngine._last_cb_log > 1.0:
                    DecodeEngine._last_cb_log = now
                    logger.warning(
                        "on_token callback failed (slot %d, %d tokens "
                        "emitted): %s", req.slot, req.generated,
                        req.on_token_error, exc_info=True)

    def _finish(self, slot: int) -> None:
        req = self._active.pop(slot)
        req.finished_at = time.monotonic()
        req.done.set()
        # Park the freed slot at length 0 so idle slots don't walk their
        # cursor toward the capacity edge while others decode.
        self.cache["length"] = self.cache["length"].at[slot].set(0)
        self._tokens[slot] = 0
        self._free.append(slot)

    def step(self) -> int:
        """Admit pending prefills, advance every active slot one token.
        Returns the number of active slots stepped."""
        import jax.numpy as jnp

        self._admit()
        if not self._active:
            return 0
        stepped = len(self._active)
        chunk = 1
        # Chunking engages when the batch can't change mid-chunk anyway
        # (no free slot for a pending request) or nothing is waiting.
        if (self.decode_chunk > 1
                and (self._pending.empty() or not self._free)
                and all(r.temperature <= 0.0
                        for r in self._active.values())):
            chunk = min(self.decode_chunk,
                        min(r.max_new_tokens - r.generated
                            for r in self._active.values()))
            # Round down to a power of two: each distinct k is its own
            # compiled program, so the program set must stay bounded
            # ({1, 2, 4, ..., decode_chunk}), not one per remaining-count.
            while chunk & (chunk - 1):
                chunk &= chunk - 1
        if chunk > 1:
            toks, self.cache = self._decode_k(
                self.params, self.cache, jnp.asarray(self._tokens),
                k=chunk)
            toks = np.asarray(toks)  # (chunk, slots)
            self.steps += chunk
            for slot in list(self._active):
                req = self._active[slot]
                for i in range(chunk):
                    tok = int(toks[i, slot])
                    self._emit(req, tok)
                    self._tokens[slot] = tok
                    if req.generated >= req.max_new_tokens or (
                            req.eos_id is not None
                            and tok == req.eos_id):
                        self._finish(slot)
                        break
            return stepped
        logits, self.cache = self._decode(
            self.params, self.cache, jnp.asarray(self._tokens))
        logits = np.asarray(logits)
        self.steps += 1
        for slot in list(self._active):
            req = self._active[slot]
            tok = self._sample_host(logits[slot], req)
            self._emit(req, tok)
            self._tokens[slot] = tok
            if req.generated >= req.max_new_tokens or (
                    req.eos_id is not None and tok == req.eos_id):
                self._finish(slot)
        return stepped

    def serve_forever(self, idle_wait_s: float = 0.05) -> None:
        """Decode loop for a replica thread: steps while work exists,
        parks on an event while idle."""
        while not self._stop.is_set():
            if self._active or not self._pending.empty():
                self.step()
            else:
                self._work.clear()
                self._work.wait(timeout=idle_wait_s)

    def shutdown(self) -> None:
        self._stop.set()
        self._work.set()

    # ------------------------------------------------------------ stats

    def stats(self) -> Dict[str, Any]:
        active = len(self._active)
        queued = self._pending.qsize()
        out = {
            "steps": self.steps,
            "tokens_out": self.tokens_out,
            "active": active,
            "slots": self.slots,
            "free_slots": len(self._free),
            "queued": queued,
            # Decode backlog as replica load: occupied slots + pending
            # queue depth. A full queue behind idle HTTP must read as
            # load to the serve autoscaler, not zero.
            "load": active + queued,
        }
        if self.prefix is not None:
            out["prefix"] = self.prefix.stats()
        return out


class LlamaDecodeDeployment:
    """Serve deployment wrapping a DecodeEngine: POST {"tokens": [...],
    "max_new_tokens": N} -> {"tokens": [...]} with streaming support
    (generator handle path). Replica-per-chip: schedule with
    ``ray_actor_options={"resources": {"TPU": 1}}``."""

    def __init__(self, preset: str = "debug", slots: int = 4,
                 capacity: int = 1024, seed: int = 0,
                 config=None, decode_chunk: int = 1,
                 prefix_pool_entries: Optional[int] = None,
                 prefix_capacity: Optional[int] = None,
                 prefix_match_min_tokens: Optional[int] = None):
        import jax

        from ray_tpu.models import llama

        cfg = config or llama.PRESETS[preset]
        self.cfg = cfg
        params = llama.init_params(cfg, jax.random.key(seed))
        self.engine = DecodeEngine(
            params, cfg, slots=slots, capacity=capacity,
            decode_chunk=decode_chunk,
            prefix_pool_entries=prefix_pool_entries,
            prefix_capacity=prefix_capacity,
            prefix_match_min_tokens=prefix_match_min_tokens)
        self._thread = threading.Thread(target=self.engine.serve_forever,
                                        name="decode-loop", daemon=True)
        self._thread.start()

    def replica_metrics(self) -> Dict[str, Any]:
        """Replica-reported load + prefix residency, merged into
        ``ReplicaActor.stats()``: the autoscaler scales on decode backlog
        and the router steers shared prefixes to the replica already
        holding them."""
        s = self.engine.stats()
        out: Dict[str, Any] = {"load": s["load"]}
        if self.engine.prefix is not None:
            out["prefix"] = s.get("prefix", {})
            out["prefixes"] = self.engine.prefix.hashes()
        return out

    def __call__(self, request: Dict[str, Any]):
        if request.get("stream"):
            # Generator return = the replica streams it (handle.stream /
            # HTTP chunked via X-Serve-Stream on this same route).
            return self.stream(request)
        req = self.engine.submit(
            request["tokens"],
            max_new_tokens=int(request.get("max_new_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"))
        req.done.wait()
        if req.error:
            raise RuntimeError(req.error)
        return {"tokens": req.output,
                "ttft_s": round(req.first_token_at - req.submitted_at, 4)}

    def stream(self, request: Dict[str, Any]):
        """Streaming generator: yields tokens as the engine emits them
        (drive via a streaming handle / HTTP chunked response)."""
        q: "queue.Queue" = queue.Queue()
        req = self.engine.submit(
            request["tokens"],
            max_new_tokens=int(request.get("max_new_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"),
            on_token=q.put)
        emitted = 0
        while True:
            try:
                tok = q.get(timeout=0.5)
                emitted += 1
                yield tok
                continue
            except queue.Empty:
                pass
            if req.done.is_set():
                while not q.empty():
                    emitted += 1
                    yield q.get()
                break

    def health(self) -> Dict[str, Any]:
        return self.engine.stats()
