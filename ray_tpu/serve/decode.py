"""Continuous-batching decode engine: the replica-side half of LLM serving.

Reference shape: the reference serves generation through its model-agnostic
replica call path + streaming (``serve/_private/replica.py:231``,
``proxy.py:761``) and leaves batching to vLLM-style engines; here the
engine is TPU-native and owns the jitted programs directly:

* ONE decode program per (slots, capacity) bucket, compiled once. Requests
  join and leave the running batch between decode steps (continuous
  batching) — a joining request's prompt is prefetched into its slot by a
  single-row prefill program, then the shared ``decode_step`` advances
  every active slot together.
* Static shapes throughout: slot count and cache capacity are fixed at
  engine construction (pick the bucket for your SLO); per-slot ``length``
  masking makes ragged occupancy exact, so there are NO recompiles at
  steady state — the serving property that matters on TPU.
* Streaming: each emitted token is pushed to the request's callback;
  ``serve``'s streaming HTTP path turns that into chunked responses.

Single-threaded by design: the engine runs inside one replica actor
(``max_concurrency`` keeps request intake concurrent; the decode loop is
the serial consumer), matching how a chip is actually scheduled.
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.core.errors import (DeadlineExceededError, OverloadedError,
                                 RequestCancelledError)

logger = logging.getLogger(__name__)

_req_ids = itertools.count(1)


@contextmanager
def _no_persistent_cache(jax_mod):
    """Fresh-compile guard: run the body with the persistent XLA
    compilation cache detached (config dir -> None + live cache handle
    reset), restoring both afterwards. jaxlib 0.4.37 reloads of DONATED
    executables from the disk cache segfault or return wrong numbers
    (pinned by PR 14's pipeline tests); every donated program this
    module compiles while a cache dir is configured routes its FIRST
    dispatch through here so it can only ever compile fresh. Resetting
    the handle matters: ``config.update(None)`` alone does not detach
    an already-initialized cache."""
    old = jax_mod.config.jax_compilation_cache_dir
    if old is None:
        yield
        return
    from jax._src import compilation_cache as _cc

    jax_mod.config.update("jax_compilation_cache_dir", None)
    _cc.reset_cache()
    try:
        yield
    finally:
        jax_mod.config.update("jax_compilation_cache_dir", old)
        _cc.reset_cache()


@dataclass(eq=False)  # identity semantics: the generated __eq__ would
#   compare numpy token arrays elementwise (list.remove on the requeue
#   would crash on different-length prompts)
class _Request:
    tokens: np.ndarray                     # prompt ids, (S,)
    max_new_tokens: int
    temperature: float
    eos_id: Optional[int]
    on_token: Optional[Callable[[int], None]]
    done: threading.Event = field(default_factory=threading.Event)
    output: List[int] = field(default_factory=list)
    slot: int = -1
    generated: int = 0
    error: Optional[str] = None
    on_token_error: Optional[str] = None   # first on_token callback failure
    submitted_at: float = field(default_factory=time.monotonic)
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    prefix_entry: int = -1                 # prefix-pool row spliced in
    prefix_len: int = 0                    # cached tokens NOT re-prefilled
    # ------------------------------------------------------- paged mode
    prefix_pages: List[int] = field(default_factory=list)  # spliced pages
    prompt_len: int = 0       # ORIGINAL prompt length (tokens grows when
    #   a preempted request re-queues with its emitted tokens absorbed)
    prefilled: int = 0        # prompt tokens prefilled so far (chunked)
    # ------------------------------------------- disaggregated handoff
    prefill_only: bool = False  # terminal = filled pages, not tokens: the
    #   request ends at first-token with its KV pages gathered to host
    #   as the handoff payload instead of entering the decode loop
    handoff: Optional[Dict[str, Any]] = None  # prefill_only result: k/v
    #   page payloads + committed_len + first_token + page geometry
    adopt: Optional[Dict[str, Any]] = None    # decode-side twin: payload
    #   to scatter into this engine's pool at admission (zero recompute)
    # --------------------------------------------------- request lifecycle
    request_id: str = ""
    deadline: Optional[float] = None       # absolute monotonic; None = none
    cancelled: bool = False                # cooperative-cancel flag
    admitted: bool = False                 # left the pending queue
    status: str = "pending"                # terminal: completed |
    #   cancelled | deadline_exceeded | error
    # --------------------------------------------------- speculative mode
    spec_proposed: int = 0                 # draft tokens proposed for this
    #   request across its spec rounds
    spec_accepted: int = 0                 # of those, verified-accepted
    # ------------------------------------------------------ observability
    trace: Optional[tuple] = None          # (trace_id, span_id) captured
    #   at submit: the engine's loop thread attributes queue-wait /
    #   prefill / decode spans back to the submitting request's trace
    admitted_at: Optional[float] = None    # first prefill dispatch
    preemptions: int = 0                   # times requeued by page pressure

    def raise_for_status(self) -> None:
        """Re-raise this request's terminal outcome as its typed error."""
        if self.status == "cancelled":
            raise RequestCancelledError(
                f"request {self.request_id} cancelled after "
                f"{self.generated} tokens")
        if self.status == "deadline_exceeded":
            raise DeadlineExceededError(
                f"request {self.request_id} exceeded its deadline after "
                f"{self.generated} tokens")
        if self.error:
            raise RuntimeError(self.error)


class DecodeEngine:
    """Continuous batcher over ``llama_decode`` programs.

    ``slots`` concurrent sequences share one KV cache of ``capacity``
    tokens per slot. ``step()`` advances every active slot one token;
    ``submit()`` enqueues a request (prefilled into a free slot at the
    next step boundary). Run ``serve_forever`` in a thread inside a
    replica, or drive ``step()`` manually in tests."""

    def __init__(self, params, config, slots: int = 4,
                 capacity: int = 1024, prefill_bucket: int = 128,
                 decode_chunk: int = 1,
                 prefix_pool_entries: Optional[int] = None,
                 prefix_capacity: Optional[int] = None,
                 prefix_match_min_tokens: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 page_tokens: Optional[int] = None,
                 pool_pages: Optional[int] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 prefix_max_pages: Optional[int] = None,
                 mesh_shape=None, mesh=None,
                 step_timeline: Optional[int] = None,
                 metrics_enabled: Optional[bool] = None,
                 trace_spans: Optional[bool] = None,
                 metrics_deployment: Optional[str] = None,
                 spec_draft_params=None, spec_draft_config=None,
                 spec_k: Optional[int] = None,
                 spec_draft_pool_pages: Optional[int] = None,
                 device_sampler: Optional[bool] = None):
        import jax

        from ray_tpu.core.config import config as rt_config
        from ray_tpu.models import llama_decode as ld
        from ray_tpu.serve.prefix_cache import PrefixCache

        self._jax = jax
        self._ld = ld
        self.params = params
        self.config = config
        self.slots = slots
        self.capacity = capacity
        self.prefill_bucket = prefill_bucket
        # ------------------------------------------- GSPMD serving mesh
        # mesh/mesh_shape turns the engine model-parallel: one replica
        # spans every device of a (batch, model) decode_mesh. Weights,
        # KV state and activations carry NamedShardings; the jitted
        # programs below get out_shardings and trace under the decode
        # axis rules (parallel.sharding.DECODE_RULES) — XLA inserts all
        # collectives, and because no contraction dim is ever
        # partitioned, logits stay BIT-EXACT vs the single-chip engine.
        if mesh is None:
            ms = mesh_shape
            if ms is None and rt_config.decode_mesh_shape:
                from ray_tpu.core.topology import parse_topology

                ms = parse_topology(rt_config.decode_mesh_shape)
            if ms is not None:
                from ray_tpu.parallel.mesh import decode_mesh

                mesh = decode_mesh(tuple(ms))
        self.mesh = mesh
        if mesh is not None:
            batch_ax = mesh.shape.get("batch", 1)
            if slots % batch_ax:
                raise ValueError(
                    f"slots ({slots}) must be a multiple of the mesh "
                    f"batch axis ({batch_ax}) — per-slot cache rows "
                    f"shard over it")
            self.params, self._shardings = ld.shard_decode_state(
                params, config, mesh)
            self._rules = self._shardings["rules"]
        else:
            self._shardings = None
            self._rules = None
        # -------------------------------------------------- paged KV pool
        # page_tokens > 0 switches from per-slot monolithic cache rows to
        # a shared device pool of fixed-size pages addressed through
        # per-slot block tables: slots hold only the pages their sequence
        # covers, prefix hits splice page ids with zero copies, and the
        # pool may be overcommitted (more slots than whole rows fit).
        pt = (rt_config.kv_page_tokens if page_tokens is None
              else page_tokens)
        self.page_tokens = int(pt)
        self.paged = self.page_tokens > 0
        chunk_tok = (rt_config.prefill_chunk_tokens
                     if prefill_chunk_tokens is None
                     else prefill_chunk_tokens)
        # Chunked-prefill interleaving rides on the paged suffix program
        # (a chunk IS a suffix prefill from pos=prefilled); contiguous
        # engines ignore it and keep monolithic admission.
        self.prefill_chunk_tokens = (int(chunk_tok) if self.paged else 0)
        if self.prefill_chunk_tokens:
            c = 1
            while c * 2 <= self.prefill_chunk_tokens:
                c *= 2
            self.prefill_chunk_tokens = c  # pow2: bounds the bucket set
        if self.paged:
            if capacity % self.page_tokens:
                raise ValueError(
                    f"capacity ({capacity}) must be a multiple of "
                    f"kv_page_tokens ({self.page_tokens})")
            from ray_tpu.serve.paging import PageAllocator

            self.slot_pages_max = capacity // self.page_tokens
            pp = (rt_config.kv_pool_pages if pool_pages is None
                  else pool_pages)
            self.pool_pages = int(pp) or slots * self.slot_pages_max
            self._pages = PageAllocator(self.pool_pages)
            pool = ld.init_page_pool(config, self.pool_pages,
                                     self.page_tokens)
            self.cache = {"k": pool["k"], "v": pool["v"],
                          "length": jax.numpy.zeros((slots,),
                                                    jax.numpy.int32)}
            self._block_tables = np.zeros(
                (slots, self.slot_pages_max), np.int32)
            self._slot_pages: List[List[int]] = [[] for _ in range(slots)]
        else:
            self._pages = None
            self.cache = ld.init_cache(config, slots, capacity)
        if self.mesh is not None:
            # Commit the KV state onto the mesh: the shared page pool
            # shards its kv-head dim over "model" (HBM-per-chip drops
            # with the model axis); contiguous rows additionally shard
            # slots over "batch". ``length`` stays replicated (bytes,
            # host-read every step).
            self._cache_sharding = dict(
                self._shardings["pool"] if self.paged
                else self._shardings["cache"])
            self.cache = jax.device_put(self.cache, self._cache_sharding)
        else:
            self._cache_sharding = None
        self._free = list(range(slots))
        self._active: Dict[int, _Request] = {}
        self._prefilling: Dict[int, _Request] = {}  # chunked, mid-prefill
        self._requeue: List[_Request] = []  # preempted/pushed-back, FIFO
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._tokens = np.zeros((slots,), np.int32)
        self._rng = np.random.default_rng(0)
        self._stop = threading.Event()
        self._work = threading.Event()
        # ------------------------------------------- request lifecycle
        # Bounded admission: past queue_max pending requests, submit()
        # sheds with OverloadedError at enqueue (<1 ms) instead of
        # queueing into minutes of latency under overload.
        if queue_max is None:
            queue_max = rt_config.decode_queue_max
        self.queue_max = int(queue_max) if queue_max else slots * 8
        # The configured cap, kept so a runtime shed override
        # (set_admission) can be lifted back to it.
        self._default_queue_max = self.queue_max
        # request_id -> live request, for cancel(); guarded by _reqs_lock
        # (intake/cancel threads vs the decode loop).
        self._requests: Dict[str, _Request] = {}
        self._reqs_lock = threading.Lock()
        self._queued_cancelled = 0  # cancelled but not yet dequeued
        self._queued_tokens = 0     # prompt tokens waiting for prefill
        #   (pending queue + requeue; advisory gauge, unlocked int ops)
        self.shed = 0               # requests rejected by the queue cap
        self.cancelled = 0          # requests ended by cancel()
        self.deadline_exceeded = 0  # requests ended by their deadline
        self.preempted = 0          # requests requeued by page pressure
        self.prefill_chunks = 0     # chunked-prefill programs dispatched
        self._ema_request_s = 0.0   # EMA of admitted-request service time
        self._last_purge = 0.0      # dead-entry queue-purge throttle
        # Prefix KV cache. Contiguous mode: a device-resident pool of
        # cached prompt-prefix K/V (P entries x C_prefix tokens) indexed
        # by a host-side trie; admission splices an entry row into the
        # slot and prefills only the suffix. Paged mode: the index pins
        # PAGE RANGES of the shared pool instead (PagedPrefixIndex) —
        # inserts and splices are zero-copy block-table edits.
        entries = (rt_config.prefix_pool_entries
                   if prefix_pool_entries is None else prefix_pool_entries)
        min_tokens = (rt_config.prefix_match_min_tokens
                      if prefix_match_min_tokens is None
                      else prefix_match_min_tokens)
        if prefix_capacity is None:
            prefix_capacity = 1
            while prefix_capacity * 2 <= capacity // 2:
                prefix_capacity *= 2
        self.prefix = None
        self._pool = None
        if self.paged:
            if entries > 0:
                from ray_tpu.serve.paging import PagedPrefixIndex

                pmax = (rt_config.kv_prefix_max_pages
                        if prefix_max_pages is None else prefix_max_pages)
                self.prefix = PagedPrefixIndex(
                    self._pages, self.page_tokens,
                    max_pages=int(pmax) or max(1, self.pool_pages // 4),
                    min_tokens=min_tokens)
        elif entries > 0 and prefix_capacity >= max(2, min_tokens):
            self.prefix = PrefixCache(entries, prefix_capacity,
                                      min_tokens=min_tokens)
            c = config
            pool_shape = (c.n_layers, entries, prefix_capacity,
                          c.n_kv_heads, c.head_dim)
            import jax.numpy as jnp
            self._pool = {"k": jnp.zeros(pool_shape, c.dtype),
                          "v": jnp.zeros(pool_shape, c.dtype)}
            if self.mesh is not None:
                self._pool = jax.device_put(
                    self._pool, self._shardings["prefix_pool"])
        # ------------------------------------------- speculative decoding
        # A draft model proposes spec_k tokens per active slot per step;
        # the target verifies all k+1 positions in ONE batched forward
        # (models.llama_decode.paged_verify) and the engine accepts the
        # longest prefix whose proposals match the target's per-position
        # argmax — greedy output is provably identical to sequential
        # decode, a step just emits 1..k+1 tokens per slot. Draft KV
        # lives in its OWN (smaller-bytes) page pool with its own block
        # tables; rejected tails roll the page cursors back on the host
        # (junk K/V past the cursor is masked and rewritten before any
        # gather, exactly like pad writes).
        sk = rt_config.spec_k if spec_k is None else spec_k
        self.spec_k = int(sk)
        self.spec = self.spec_k > 0 and spec_draft_params is not None
        if self.spec:
            if not self.paged:
                raise ValueError(
                    "speculative decoding requires paged KV "
                    "(kv_page_tokens > 0): the verify forward and the "
                    "rollback cursor are page-table operations")
            from ray_tpu.serve.paging import PageAllocator
            self._draft_config = spec_draft_config
            dpp = (rt_config.spec_draft_pool_pages
                   if spec_draft_pool_pages is None
                   else spec_draft_pool_pages)
            self.draft_pool_pages = int(dpp) or self.pool_pages
            self._draft_pages = PageAllocator(self.draft_pool_pages)
            dpool = ld.init_page_pool(spec_draft_config,
                                      self.draft_pool_pages,
                                      self.page_tokens)
            self._draft_cache = {
                "k": dpool["k"], "v": dpool["v"],
                "length": jax.numpy.zeros((slots,), jax.numpy.int32)}
            self._draft_bt = np.zeros((slots, self.slot_pages_max),
                                      np.int32)
            self._draft_slot_pages: List[List[int]] = [
                [] for _ in range(slots)]
            # Host-side committed draft length per slot; -1 = draftless
            # (the draft pool could not seat it — the slot rides spec
            # rounds with junk proposals that simply get rejected).
            self._draft_committed = [0] * slots
            self._draft_params = spec_draft_params
            self._draft_rules = None
            self._draft_cache_sharding = None
            if self.mesh is not None:
                self._draft_params, dsh = ld.shard_decode_state(
                    spec_draft_params, spec_draft_config, mesh)
                self._draft_rules = dsh["rules"]
                self._draft_cache_sharding = dict(dsh["pool"])
                self._draft_cache = jax.device_put(
                    self._draft_cache, self._draft_cache_sharding)
            self.spec_rounds = 0
            self.spec_proposed = 0
            self.spec_accepted = 0
        else:
            self.spec = False
        # Device-side sampling: the decode program returns token ids
        # (argmax / per-row categorical fused under out_shardings)
        # instead of (slots, vocab) logits — the host stops paying a
        # full-vocab transfer per step. Opt-in: greedy rows are
        # bit-identical either way, sampled rows move to the device RNG
        # stream.
        self._device_sampler = bool(
            rt_config.decode_device_sampler if device_sampler is None
            else device_sampler)
        self._tokens_dev = None  # device-resident next-token vector:
        #   valid between consecutive device-sampled steps (the program's
        #   output feeds the next call without a host->device upload);
        #   ANY host-side token write invalidates it.
        # Suffix prefills bucket on a finer grid than full prefills: the
        # whole point is that the suffix is short, so padding it back up
        # to prefill_bucket would refund most of the win.
        self._suffix_bucket_min = max(8, min(16, prefill_bucket))
        # Per-(bucket) jitted single-slot prefill: writes one row of the
        # shared cache. Donating the cache makes the slot insert in-place.
        # Params are ARGUMENTS (not closure captures), or jit would bake
        # the weights into the program as constants.
        # Mesh engines pin program outputs to the committed shardings
        # (logits/token outputs replicated for the host sampler, KV
        # state staying exactly where device_put placed it, so
        # donation reuses the sharded buffers); single-chip engines
        # pass no shardings at all — their jaxprs are byte-identical
        # to pre-mesh builds.
        if self.mesh is not None:
            rep = self._shardings["replicated"]
            cache_out = {"out_shardings": (rep, self._cache_sharding)}
            pool_ins = {"out_shardings": (
                self._shardings["prefix_pool"]["k"],
                self._shardings["prefix_pool"]["v"])}
        else:
            cache_out = {}
            pool_ins = {}
        if self.paged:
            # Paged programs: same (n, bucket) jit-bucket discipline, but
            # admission scatters K/V into pool pages through the wave's
            # block tables, the suffix program doubles as the chunked-
            # prefill continuation, and decode gathers each slot's pages
            # back into logical order (bit-exact vs the contiguous dot).
            # ``width`` (suffix) = static leading block-table columns the
            # wave touches — cost scales with prefix+suffix, not max
            # context, exactly like the contiguous ``lim``.
            self._paged_prefill = self._mesh_scoped(jax.jit(
                self._paged_prefill_impl, static_argnames=("n", "bucket"),
                donate_argnums=(1,), **cache_out))
            self._paged_suffix = self._mesh_scoped(jax.jit(
                self._paged_suffix_impl,
                static_argnames=("n", "bucket", "width"),
                donate_argnums=(1,), **cache_out))
            self._decode = self._mesh_scoped(jax.jit(
                self._paged_decode_impl, donate_argnums=(1,),
                **cache_out))
            # Disaggregated adopt: scatter handed-off page payloads into
            # the pool (pure data movement, no model math) and park the
            # slot cursor at the committed length. Cache-only output, so
            # mesh engines pin just the cache sharding (the
            # draft_cache_only precedent below).
            self._adopt_pages = self._mesh_scoped(jax.jit(
                self._adopt_pages_impl, static_argnames=("width",),
                donate_argnums=(0,),
                **({"out_shardings": self._cache_sharding}
                   if self.mesh is not None else {})))
        else:
            self._prefill_many = self._mesh_scoped(jax.jit(
                self._prefill_many_impl, static_argnames=("n", "bucket"),
                donate_argnums=(1,), **cache_out))
            # Prefix-hit admission: splice pool entries into the wave's
            # slots and prefill only the suffixes — one program per
            # (n, bucket) power-of-two pair, like _prefill_many. Pool
            # insert copies a freshly prefilled slot's leading positions
            # into a pool row.
            self._prefill_suffix_many = self._mesh_scoped(jax.jit(
                self._prefill_suffix_many_impl,
                static_argnames=("n", "bucket"), donate_argnums=(1,),
                **cache_out))
            self._pool_insert = self._mesh_scoped(jax.jit(
                self._pool_insert_impl, donate_argnums=(1, 2),
                **pool_ins))
            self._decode = self._mesh_scoped(jax.jit(
                self._decode_impl, donate_argnums=(1,), **cache_out))
        # K greedy steps per device call (dispatch amortization); chunking
        # only engages when no admissions are pending and every active
        # request is greedy — sampling and joins stay per-token exact.
        self.decode_chunk = max(1, int(decode_chunk))
        self._decode_k = self._mesh_scoped(jax.jit(
            self._paged_decode_chunk_impl if self.paged
            else self._decode_chunk_impl,
            static_argnames=("k",), donate_argnums=(1,), **cache_out))
        # Speculative programs: target verify (all-position argmax over
        # the slot's pages, donated KV) and the draft's own prefill +
        # catch-up/propose programs against the draft pool. Both sample
        # on device — a round moves (slots, k+1) int32 to the host, not
        # logits.
        if self.spec:
            if self.mesh is not None:
                draft_out = {"out_shardings": (
                    rep, self._draft_cache_sharding)}
                # _draft_prefill returns ONLY the draft cache (its
                # logits are discarded in-program).
                draft_cache_only = {
                    "out_shardings": self._draft_cache_sharding}
            else:
                draft_out = {}
                draft_cache_only = {}
            self._spec_verify = self._mesh_scoped(jax.jit(
                self._spec_verify_impl, donate_argnums=(1,),
                **cache_out))
            self._spec_draft = self._mesh_scoped(jax.jit(
                self._spec_draft_impl, static_argnames=("k",),
                donate_argnums=(1,), **draft_out),
                rules=self._draft_rules)
            self._draft_prefill = self._mesh_scoped(jax.jit(
                self._draft_prefill_impl,
                static_argnames=("n", "bucket"), donate_argnums=(1,),
                **draft_cache_only), rules=self._draft_rules)
        # Fused device sampler (paged and contiguous flavors): one
        # program returning sampled token ids; per-row temperatures pick
        # argmax vs categorical, the PRNG key derives from the step
        # counter in-program.
        if self._device_sampler:
            self._decode_sampled = self._mesh_scoped(jax.jit(
                self._paged_decode_sampled_impl if self.paged
                else self._decode_sampled_impl, donate_argnums=(1,),
                **cache_out))
        self.steps = 0
        self.tokens_out = 0
        # ---------------------------------------------- observability
        # SLO metrics + trace spans are per-REQUEST (terminal outcomes,
        # admission, per-wave prefills) and the step recorder is one
        # deque append per step — nothing here touches the per-token
        # path, so the decode loop's cost is unchanged at steady state
        # (bench_decode.py --sections trace_overhead pins <2%).
        from ray_tpu.serve.replica import replica_ident
        from ray_tpu.serve.steplog import StepTimeline

        self._obs_metrics = (rt_config.serve_metrics_enabled
                             if metrics_enabled is None else metrics_enabled)
        self._obs_spans = (rt_config.serve_trace_spans
                           if trace_spans is None else trace_spans)
        ident = replica_ident()
        self._mtags = {"deployment": (metrics_deployment
                                      or ident["deployment"] or "-")}
        self._replica_id = ident["replica_id"]
        self.steplog = StepTimeline(
            rt_config.decode_step_timeline
            if step_timeline is None else step_timeline)
        self._compiled: set = set()  # program keys dispatched once
        self._prefill_waves = 0      # prefill programs dispatched
        # Disaggregated handoff accounting (engine side; the per-replica
        # lease ledger lives on the deployment wrapper).
        self.handoffs_published = 0  # prefill_only captures completed
        self.handoffs_adopted = 0    # adopted seats completed
        self._handoff_phases: List[Dict[str, Any]] = []  # pending steplog
        #   phase rows, drained into the next _steplog_row

    def _mesh_scoped(self, fn, rules=None):
        """Mesh engines trace every program inside the decode axis-rules
        context (``constrain`` sites in the model resolve against it);
        single-chip engines get the callable back untouched. ``rules``
        overrides the table for programs of a DIFFERENT config than the
        target — the spec draft model resolves its own divisibility
        specialization of DECODE_RULES."""
        if self.mesh is None:
            return fn
        from ray_tpu.parallel.sharding import axis_rules

        table = self._rules if rules is None else rules

        def scoped(*args, **kwargs):
            with axis_rules(self.mesh, table):
                return fn(*args, **kwargs)

        return scoped

    # ------------------------------------------------------ jitted bodies

    def _prefill_many_impl(self, params, cache, tokens_rows, lengths,
                           slot_ids, n, bucket):
        """Batched admission: prefill ``n`` rows in ONE device call and
        scatter their K/V into the shared cache at ``slot_ids``. One
        compiled program per (n, bucket) power-of-two pair — dispatch
        overhead amortizes over the whole admission wave."""
        ld, cfg = self._ld, self.config
        batch = ld.init_cache(cfg, n, self.capacity)
        logits, batch = ld.prefill(params, tokens_rows[:, :bucket],
                                   batch, cfg, lengths=lengths)
        s = batch["k"].shape[2]
        new = {
            "k": cache["k"].at[:, slot_ids, :s].set(batch["k"]),
            "v": cache["v"].at[:, slot_ids, :s].set(batch["v"]),
            "length": cache["length"].at[slot_ids].set(lengths),
        }
        return logits, new

    def _prefill_suffix_many_impl(self, params, cache, pool_k, pool_v,
                                  entry_ids, slot_ids, suffix_rows,
                                  prefix_lens, lengths, n, bucket):
        """Prefix-hit admission in ONE device call: gather the wave's
        slot rows, splice the matched pool entries over their leading
        ``C_prefix`` positions, suffix-prefill from ``pos=prefix_lens``,
        and scatter the rows back. The splice copies the WHOLE entry
        region unconditionally (static shape): positions past the match
        are overwritten by the suffix or causally masked, never read."""
        ld = self._ld
        cp = pool_k.shape[2]
        # Every read/write in this program lands below prefix+suffix
        # (prefix_lens <= C_prefix, suffix spans `bucket`), so the
        # gather, attention, and scatter run over that STATIC bound
        # instead of the full capacity — the suffix path's cost scales
        # with what it touches, not with the engine's max context.
        lim = min(self.capacity, cp + bucket)
        rows_k = cache["k"][:, slot_ids, :lim]    # (L, n, lim, KV, D)
        rows_v = cache["v"][:, slot_ids, :lim]
        rows_k = rows_k.at[:, :, :cp].set(pool_k[:, entry_ids])
        rows_v = rows_v.at[:, :, :cp].set(pool_v[:, entry_ids])
        row_cache = {"k": rows_k, "v": rows_v, "length": lengths}
        logits, row_cache = ld.prefill_suffix(
            params, suffix_rows[:, :bucket], row_cache, self.config,
            prefix_lens, lengths)
        new = {
            "k": cache["k"].at[:, slot_ids, :lim].set(row_cache["k"]),
            "v": cache["v"].at[:, slot_ids, :lim].set(row_cache["v"]),
            "length": cache["length"].at[slot_ids].set(lengths),
        }
        return logits, new

    def _pool_insert_impl(self, cache, pool_k, pool_v, slot, entry):
        cp = pool_k.shape[2]
        new_k = pool_k.at[:, entry].set(cache["k"][:, slot, :cp])
        new_v = pool_v.at[:, entry].set(cache["v"][:, slot, :cp])
        return new_k, new_v

    def _decode_impl(self, params, cache, tokens):
        return self._ld.decode_step(params, cache, tokens, self.config)

    def _decode_chunk_impl(self, params, cache, tokens, k):
        return self._ld.decode_chunk(params, cache, tokens, self.config,
                                     k)

    # ------------------------------------------------ paged jitted bodies

    def _paged_prefill_impl(self, params, cache, tokens_rows, lengths,
                            bt, slot_ids, n, bucket):
        """Batched paged admission: causal prefill of ``n`` prompts in
        ONE device call, K/V scattered into the pool pages ``bt`` maps
        (one program per (n, bucket) power-of-two pair)."""
        ld = self._ld
        pool = {"k": cache["k"], "v": cache["v"]}
        logits, pool = ld.paged_prefill(params, tokens_rows[:, :bucket],
                                        pool, bt, self.config,
                                        lengths=lengths)
        return logits, {
            "k": pool["k"], "v": pool["v"],
            "length": cache["length"].at[slot_ids].set(lengths),
        }

    def _paged_suffix_impl(self, params, cache, tokens_rows, prefix_lens,
                           lengths, bt, slot_ids, n, bucket, width):
        """Suffix prefill over paged context: the prefix-hit splice
        (shared pages arrive through ``bt`` — the block table IS the
        splice, no copies) and the chunked-prefill continuation step.
        ``bt`` is pre-sliced to ``width`` leading page columns so
        gather/attention cost scales with prefix + suffix."""
        ld = self._ld
        pool = {"k": cache["k"], "v": cache["v"]}
        logits, pool = ld.paged_prefill_suffix(
            params, tokens_rows[:, :bucket], pool, bt, self.config,
            prefix_lens, lengths)
        return logits, {
            "k": pool["k"], "v": pool["v"],
            "length": cache["length"].at[slot_ids].set(lengths),
        }

    def _paged_decode_impl(self, params, cache, tokens, bt):
        pool = {"k": cache["k"], "v": cache["v"]}
        logits, pool, lens = self._ld.paged_decode_step(
            params, pool, bt, cache["length"], tokens, self.config)
        return logits, {"k": pool["k"], "v": pool["v"], "length": lens}

    def _adopt_pages_impl(self, cache, k_pages, v_pages, ids, slot_ids,
                          lengths, width):
        """Adopt a handed-off prefill: scatter ``width`` page payloads
        into the pool at ``ids`` and park the slot cursor at the
        committed length. Pure data movement — no model math — so the
        adopted state is bit-identical to having prefilled locally.
        Pad columns target the scratch page (id 0, never read) with
        zero payloads; ``width`` is the pow-2 compile bucket."""
        return {
            "k": cache["k"].at[:, ids].set(k_pages),
            "v": cache["v"].at[:, ids].set(v_pages),
            "length": cache["length"].at[slot_ids].set(lengths),
        }

    def _paged_decode_chunk_impl(self, params, cache, tokens, bt, k):
        pool = {"k": cache["k"], "v": cache["v"]}
        toks, pool, lens = self._ld.paged_decode_chunk(
            params, pool, bt, cache["length"], tokens, self.config, k)
        return toks, {"k": pool["k"], "v": pool["v"], "length": lens}

    # ------------------------------------------- speculative jitted bodies

    def _spec_verify_impl(self, params, cache, rows, bt):
        """Target verify forward: rows (slots, k+1) laid out as
        ``[last_emitted, draft_1..draft_k]`` per slot, scored from
        ``pos = length`` against the slot's pages, argmax fused on
        device — the host receives (slots, k+1) token ids, never
        logits. ``length`` is returned UNCHANGED: the host owns the
        cursor and rolls it forward only over the accepted run."""
        import jax.numpy as jnp

        pool = {"k": cache["k"], "v": cache["v"]}
        logits, pool = self._ld.paged_verify(
            params, rows, pool, bt, self.config, cache["length"])
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return toks, {"k": pool["k"], "v": pool["v"],
                      "length": cache["length"]}

    def _spec_draft_impl(self, params, cache, catchup, catchup_lens,
                         bt, k):
        """Draft propose: ingest each slot's 1-2 catch-up tokens from
        ``pos = length`` and greedily roll ``k`` proposals against the
        draft pool. ``length`` is host-owned (rolled back with the
        target's cursor after acceptance) — returned unchanged."""
        pool = {"k": cache["k"], "v": cache["v"]}
        toks, pool = self._ld.paged_spec_draft(
            params, pool, bt, cache["length"], catchup, catchup_lens,
            self._draft_config, k)
        return toks, {"k": pool["k"], "v": pool["v"],
                      "length": cache["length"]}

    def _draft_prefill_impl(self, params, cache, tokens_rows, lengths,
                            bt, slot_ids, n, bucket):
        """Draft-pool prompt prefill at admission: the draft must hold
        K/V for the WHOLE prompt (target prefix-cache hits don't help
        it — the draft pool has no prefix index), which is fine because
        the draft is the model chosen to be cheap."""
        ld = self._ld
        pool = {"k": cache["k"], "v": cache["v"]}
        _, pool = ld.paged_prefill(params, tokens_rows[:, :bucket],
                                   pool, bt, self._draft_config,
                                   lengths=lengths)
        return {"k": pool["k"], "v": pool["v"],
                "length": cache["length"].at[slot_ids].set(lengths)}

    def _paged_decode_sampled_impl(self, params, cache, tokens, bt,
                                   temps, step):
        import jax

        pool = {"k": cache["k"], "v": cache["v"]}
        logits, pool, lens = self._ld.paged_decode_step(
            params, pool, bt, cache["length"], tokens, self.config)
        key = jax.random.fold_in(jax.random.key(0), step)
        toks = self._ld.sample_batch(logits, temps, key)
        return toks, {"k": pool["k"], "v": pool["v"], "length": lens}

    def _decode_sampled_impl(self, params, cache, tokens, temps, step):
        import jax

        logits, cache = self._ld.decode_step(params, cache, tokens,
                                             self.config)
        key = jax.random.fold_in(jax.random.key(0), step)
        toks = self._ld.sample_batch(logits, temps, key)
        return toks, cache

    def _dispatch_fresh(self, key: tuple, call):
        """First dispatch of one of this PR's donated programs compiles
        with the persistent XLA compilation cache DETACHED (jaxlib
        0.4.37 pin, PR 14: a donated executable reloaded from the disk
        cache segfaults or returns wrong numbers — the tier-1 conftest
        only dodges it because sub-second debug-model compiles never
        persist). Later dispatches hit the live in-process jit cache
        and never touch disk."""
        if key in self._compiled:
            return call()
        self._mark_compile(key)
        with _no_persistent_cache(self._jax):
            return call()

    # --------------------------------------------- paged page accounting

    def _alloc_pages(self, n: int) -> Optional[List[int]]:
        """``n`` pool pages, reclaiming prefix-index pins under pressure.
        None = genuinely dry (caller preempts or backs off)."""
        if self._pages.free_count < n and self.prefix is not None:
            self.prefix.reclaim(n - self._pages.free_count)
        got = self._pages.alloc(n)
        if got is None:
            return None
        try:
            if self.steplog.enabled:
                self.steplog.event("page-alloc", n=n,
                                   free=self._pages.free_count)
        except BaseException:
            # Exception-safety for the lease: an event-recording
            # failure must hand the pages back, not strand them.
            self._pages.free(got)
            raise
        return got

    def _set_slot_pages(self, slot: int, pages: List[int]) -> None:
        self._block_tables[slot, :] = 0
        self._block_tables[slot, :len(pages)] = pages
        self._slot_pages[slot] = pages

    def _grow_slot(self, slot: int, pages: List[int]) -> None:
        have = self._slot_pages[slot]
        self._block_tables[slot, len(have):len(have) + len(pages)] = pages
        self._slot_pages[slot] = have + pages

    def _seq_pages(self, tokens: int) -> int:
        return -(-tokens // self.page_tokens)

    def _ensure_decode_pages(self, k: int) -> None:
        """Every active slot can write its next ``k`` tokens. Oldest
        slots are served first; when the pool is dry even after
        reclaiming prefix pins, the YOUNGEST admitted request is
        preempted (recompute-style requeue) — the oldest request always
        makes progress, so this terminates."""
        for slot in sorted(self._active,
                           key=lambda s: self._active[s].submitted_at):
            while True:
                req = self._active.get(slot)
                if req is None:
                    break  # preempted while serving an older slot
                need = self._seq_pages(req.prompt_len + req.generated
                                       - 1 + k) \
                    - len(self._slot_pages[slot])
                if need <= 0:
                    break
                got = self._alloc_pages(need)
                if got is not None:
                    self._grow_slot(slot, got)
                    break
                if not self._preempt_one():
                    break  # nothing left to preempt: caller's slot only

    # ------------------------------------------- draft-pool accounting
    #
    # The draft pool mirrors the target's block-table discipline at the
    # draft model's (smaller) K/V width: same page size, its own
    # allocator and tables, no prefix index. Freeing a slot frees both
    # pools. Draft-pool pressure NEVER touches the target plane: a
    # draft seat is opportunistic (it only buys speedup), so a dry
    # draft pool evicts the youngest DRAFT seat — never preempts a
    # request, which would requeue it through the suffix-continuation
    # prefill and perturb greedy near-ties.

    def _draft_grow_slot(self, slot: int, pages: List[int]) -> None:
        have = self._draft_slot_pages[slot]
        self._draft_bt[slot, len(have):len(have) + len(pages)] = pages
        self._draft_slot_pages[slot] = have + pages

    def _ensure_draft_pages(self, k: int) -> None:
        """Every drafted active slot's draft can write catch-up + k-1
        proposal positions (through ``L + k - 1``). Draftless slots (-1)
        are skipped: their rows route to the scratch page and their junk
        proposals are simply rejected by verification. A slot the pool
        cannot cover even after evicting younger draft seats is demoted
        to draftless the same way — spec rounds stay correct
        (verification guarantees the output), the slot just stops
        speculating usefully."""
        for slot in sorted(self._active,
                           key=lambda s: self._active[s].submitted_at):
            req = self._active[slot]
            while True:
                if self._draft_committed[slot] < 0:
                    break
                need = self._seq_pages(req.prompt_len + req.generated
                                       - 1 + k) \
                    - len(self._draft_slot_pages[slot])
                if need <= 0:
                    break
                got = self._draft_pages.alloc(need)
                if got is not None:
                    self._draft_grow_slot(slot, got)
                    break
                if not self._draft_evict_one(slot):
                    self._draft_demote(slot, req)
                    break

    def _draft_demote(self, slot: int, req: _Request) -> None:
        """Drop a slot's draft seat (freeing its draft pages): it keeps
        riding spec rounds with junk proposals that verification
        rejects — output stays correct, the slot just stops
        contributing speedup until re-admission reseats it."""
        self._draft_pages.free(self._draft_slot_pages[slot])
        self._draft_slot_pages[slot] = []
        self._draft_bt[slot, :] = 0
        self._draft_committed[slot] = -1
        if self.steplog.enabled:
            self.steplog.event("spec-draftless",
                               request=req.request_id)

    def _draft_evict_one(self, keep: int) -> bool:
        """Make room in the draft pool by demoting the youngest OTHER
        drafted slot. Never touches the target plane — preempting a
        request over draft pressure would requeue it through the
        suffix-continuation prefill and perturb greedy near-ties,
        breaking the spec-mode bit-exactness contract for pure
        speedup bookkeeping."""
        cands = [s for s in self._active
                 if s != keep and self._draft_committed[s] >= 0
                 and self._draft_slot_pages[s]]
        if not cands:
            return False
        victim = max(cands, key=lambda s: self._active[s].submitted_at)
        self._draft_demote(victim, self._active[victim])
        return True

    def _rollback_pages(self, slot: int, committed: int) -> None:
        """Roll a slot's page cursors back to ``committed`` tokens after
        a spec round: tail pages past the accepted run free in BOTH
        pools (their junk K/V is provably dead — nothing attends past
        the rolled-back ``length``, and a later owner's scatter runs
        before its gather). Leading pages — including shared prefix
        splices — are never touched: ``committed >= prefix_len``
        always."""
        keep = self._seq_pages(committed)
        tail = self._slot_pages[slot][keep:]
        if tail:
            self._block_tables[slot, keep:keep + len(tail)] = 0
            self._slot_pages[slot] = self._slot_pages[slot][:keep]
            self._pages.free(tail)
        keep_d = self._seq_pages(min(committed,
                                     self._draft_committed[slot]))
        dtail = self._draft_slot_pages[slot][keep_d:]
        if dtail:
            self._draft_bt[slot, keep_d:keep_d + len(dtail)] = 0
            self._draft_slot_pages[slot] = \
                self._draft_slot_pages[slot][:keep_d]
            self._draft_pages.free(dtail)

    def _preempt_one(self) -> bool:
        """Requeue the youngest admitted request to free its pages
        (vLLM-style recompute preemption): its prompt plus every token
        emitted so far re-enters the queue as one prefill, so the
        stream continues exactly where it left off after re-admission."""
        cands = list(self._active.items()) + list(self._prefilling.items())
        if not cands:
            return False
        slot, req = max(cands, key=lambda it: it[1].submitted_at)
        self._active.pop(slot, None)
        self._prefilling.pop(slot, None)
        self._release_slot(slot)
        absorbed = len(req.tokens) - req.prompt_len
        tail = np.asarray(req.output[absorbed:], np.int32)
        if len(tail):
            req.tokens = np.concatenate([req.tokens, tail])
        req.slot = -1
        req.prefix_pages = []
        req.prefix_len = 0
        req.prefilled = 0
        self.preempted += 1
        req.preemptions += 1
        if self._obs_metrics:
            from ray_tpu.serve import metrics as smetrics

            smetrics.PREEMPTIONS.inc(1.0, self._mtags)
        if self.steplog.enabled:
            self.steplog.event("preempt", request=req.request_id,
                               tokens=req.generated)
        if self._obs_spans and req.trace is not None:
            from ray_tpu.util import tracing

            now = time.time()
            tracing.record_span("preempt", now, now, ctx=req.trace,
                                request=req.request_id,
                                tokens=req.generated)
        self._requeue.insert(0, req)
        self._queued_tokens += len(req.tokens)
        with self._reqs_lock:
            req.admitted = False  # cancel-while-requeued counts as queued
        self._work.set()
        return True

    # ------------------------------------------------------------ intake

    def set_admission(self, queue_max: Optional[int]) -> int:
        """Runtime admission-cap override (the autopilot shed-tenant
        action, via ReplicaActor.set_admission): requests past the new
        cap shed at enqueue with OverloadedError. ``None``/``0``
        restores the configured default. Returns the cap in effect."""
        self.queue_max = (max(1, int(queue_max)) if queue_max
                          else self._default_queue_max)
        return self.queue_max

    def submit(self, prompt_tokens, max_new_tokens: int = 32,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               on_token: Optional[Callable[[int], None]] = None,
               deadline_s: Optional[float] = None,
               request_id: Optional[str] = None,
               prefill_only: bool = False,
               adopt: Optional[Dict[str, Any]] = None) -> _Request:
        req = _Request(np.asarray(prompt_tokens, np.int32).reshape(-1),
                       int(max_new_tokens), float(temperature), eos_id,
                       on_token)
        req.request_id = request_id or f"req-{next(_req_ids)}"
        req.prompt_len = len(req.tokens)
        if prefill_only and not self.paged:
            raise ValueError("prefill_only handoff requires a paged "
                             "engine (kv_page_tokens > 0)")
        req.prefill_only = bool(prefill_only)
        if adopt is not None:
            self._validate_adopt(req, adopt)
            req.adopt = dict(adopt)
        if self.paged and self._seq_pages(
                len(req.tokens) + req.max_new_tokens) > self.pool_pages:
            # A request no amount of preemption can seat must fail fast,
            # not live forever in the requeue list.
            raise ValueError(
                f"prompt ({len(req.tokens)}) + max_new_tokens "
                f"({req.max_new_tokens}) needs more pages than the pool "
                f"holds ({self.pool_pages} x {self.page_tokens} tokens)")
        # The spec draft pool is deliberately NOT an admission limit: a
        # request the draft pool cannot seat decodes draftless (junk
        # proposals, all rejected) — correct output, no speedup.
        if len(req.tokens) >= self.capacity:
            raise ValueError(
                f"prompt ({len(req.tokens)}) must be shorter than the "
                f"cache capacity ({self.capacity})")
        if len(req.tokens) + req.max_new_tokens > self.capacity:
            # Past capacity the K/V scatter at pos=length goes out of
            # bounds and JAX silently drops it — the request would return
            # wrong tokens, not an error. generate() sizes its cache as
            # cache_bucket(S + max_new_tokens); the engine's cache is
            # fixed, so the same budget must hold at admission.
            raise ValueError(
                f"prompt ({len(req.tokens)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds the cache capacity "
                f"({self.capacity})")
        if deadline_s is not None:
            if deadline_s <= 0:
                self.deadline_exceeded += 1
                if self._obs_metrics:
                    from ray_tpu.serve import metrics as smetrics

                    smetrics.REQUESTS.inc(1.0, {
                        **self._mtags, "outcome": "deadline_exceeded"})
                raise DeadlineExceededError(
                    f"request {req.request_id} arrived with an already-"
                    f"expired deadline ({deadline_s:.3f}s)")
            req.deadline = time.monotonic() + float(deadline_s)
        if self._obs_spans:
            from ray_tpu.util import tracing

            req.trace = tracing.current()  # loop-thread spans attach here
        # Load shedding happens HERE, at enqueue — not after minutes in
        # queue. qsize() can transiently overshoot by concurrent
        # submitters, but the check bounds the queue within one wave.
        if self._pending.qsize() - self._queued_cancelled >= self.queue_max:
            self.shed += 1
            if self._obs_metrics:
                from ray_tpu.serve import metrics as smetrics

                smetrics.REQUESTS.inc(1.0, {**self._mtags,
                                            "outcome": "shed"})
            if req.trace is not None:
                from ray_tpu.util import tracing

                now = time.time()
                tracing.record_span("engine-shed", now, now,
                                    ctx=req.trace,
                                    request=req.request_id)
            raise OverloadedError(
                f"decode queue at capacity ({self.queue_max} pending, "
                f"{self.slots} slots)",
                retry_after_s=self.retry_after_estimate_s())
        with self._reqs_lock:
            self._requests[req.request_id] = req
        self._queued_tokens += len(req.tokens)
        self._pending.put(req)
        self._work.set()
        return req

    def _validate_adopt(self, req: _Request,
                        adopt: Dict[str, Any]) -> None:
        """Reject a handoff this pool cannot splice BEFORE enqueue, as
        the typed error the router maps to its colocated fallback. The
        payload must have been gathered from a pool with identical page
        geometry and head layout, and must cover exactly the prompt."""
        from ray_tpu.core.errors import HandoffAdoptError

        if not self.paged:
            raise HandoffAdoptError(
                "adopt requires a paged engine (kv_page_tokens > 0)")
        if int(adopt["page_tokens"]) != self.page_tokens:
            raise HandoffAdoptError(
                f"handoff page_tokens ({adopt['page_tokens']}) != this "
                f"engine's ({self.page_tokens}); pages cannot splice")
        if int(adopt["committed_len"]) != len(req.tokens):
            raise HandoffAdoptError(
                f"handoff committed_len ({adopt['committed_len']}) != "
                f"prompt length ({len(req.tokens)})")
        k = adopt["k"]
        pool = self.cache["k"].shape  # (L, pages+1, T, KV, D)
        if (k.ndim != 5 or k.shape[0] != pool[0]
                or tuple(k.shape[2:]) != tuple(pool[2:])
                or k.shape[1] != self._seq_pages(len(req.tokens))):
            raise HandoffAdoptError(
                f"handoff payload shape {tuple(k.shape)} does not fit "
                f"this engine's pool {tuple(pool)}")

    def retry_after_estimate_s(self) -> float:
        """How long a shed caller should wait before retrying, from the
        observed per-request service time: the queue drains ``slots``
        requests per service interval, so a rejected request's turn is
        about ``(queued / slots + 1)`` intervals away. Clamped to
        [0.5 s, 30 s]; 1 s before any request has completed."""
        if self._ema_request_s <= 0:
            return 1.0
        depth = max(0, self._pending.qsize() - self._queued_cancelled)
        est = (depth / max(1, self.slots) + 1.0) * self._ema_request_s
        return min(30.0, max(0.5, est))

    def cancel(self, request_id: str) -> bool:
        """Cooperative cancellation: mark the request; the decode loop
        drops it before prefill if still queued, or frees its slot at the
        next ``step()`` boundary if active. Returns False for unknown /
        already-finished requests (cancel is idempotent)."""
        with self._reqs_lock:
            req = self._requests.get(request_id)
            if req is None or req.done.is_set() or req.cancelled:
                return False
            req.cancelled = True
            if not req.admitted:
                # Still in the pending queue: exclude it from the load
                # signal now; _admit reconciles when it dequeues it.
                self._queued_cancelled += 1
        self._work.set()  # wake a parked loop so the drop is prompt
        return True

    # ------------------------------------------------ observability hooks
    #
    # All per-request: admission (queue-wait), wave prefills, terminal
    # outcomes. The per-token and per-step paths never touch the metrics
    # registry or the task-event buffer.

    def _mark_admitted(self, reqs: List["_Request"]) -> None:
        """Queue wait ends: the wave is about to dispatch device work.
        First admission only — a preemption requeue keeps its original
        admission time (queue_wait measures admission latency, not
        lifetime)."""
        fresh = [r for r in reqs if r.admitted_at is None]
        if not fresh:
            return
        now = time.monotonic()
        for req in fresh:
            req.admitted_at = now
        if self._obs_metrics:
            from ray_tpu.serve import metrics as smetrics

            smetrics.QUEUE_WAIT.observe_many(
                [now - r.submitted_at for r in fresh], self._mtags)
        if self._obs_spans:
            from ray_tpu.util import tracing

            wall = time.time()
            for req in fresh:
                if req.trace is not None:
                    tracing.record_span(
                        "queue-wait", wall - (now - req.submitted_at),
                        wall, ctx=req.trace, request=req.request_id)

    def _wave_span(self, name: str, t0_wall: float,
                   reqs: List["_Request"], **attrs: Any) -> None:
        """One span per request of a batched device call (the wave is
        one program; each request's trace gets its own slice of it)."""
        if not self._obs_spans:
            return
        from ray_tpu.util import tracing

        t1 = time.time()
        for req in reqs:
            if req.trace is not None:
                tracing.record_span(name, t0_wall, t1, ctx=req.trace,
                                    request=req.request_id, **attrs)

    def _mark_compile(self, key: tuple) -> None:
        """First dispatch of a program key = a jit compile on this
        engine; later dispatches of the same key are cache hits."""
        if key not in self._compiled:
            self._compiled.add(key)
            if self.steplog.enabled:
                self.steplog.event("jit-compile", key="/".join(
                    str(k) for k in key))

    def _observe_terminal(self, req: "_Request", status: str) -> None:
        """Terminal bookkeeping shared by _finish and _retire: outcome
        counter, TTFT / inter-token histograms, and the request's
        engine-side spans (decode slice + whole-request outcome)."""
        if self._obs_metrics:
            from ray_tpu.serve import metrics as smetrics

            smetrics.REQUESTS.inc(1.0, {**self._mtags, "outcome": status})
            if req.first_token_at is not None:
                smetrics.TTFT.observe(
                    req.first_token_at - req.submitted_at, self._mtags)
                if status == "completed" and req.generated > 1:
                    # Stream duration / token, once per request: robust
                    # to chunked emission's bursty raw gaps, and never a
                    # per-token registry hit.
                    smetrics.INTER_TOKEN.observe(
                        (req.finished_at - req.first_token_at)
                        / (req.generated - 1), self._mtags)
            if req.spec_proposed > 0:
                # Acceptance per REQUEST (not per round): one histogram
                # observation at terminal keeps the doctrine — nothing
                # observability-side runs per token or per step.
                smetrics.SPEC_PROPOSED.inc(float(req.spec_proposed),
                                           self._mtags)
                smetrics.SPEC_ACCEPTED.inc(float(req.spec_accepted),
                                           self._mtags)
                smetrics.SPEC_ACCEPT.observe(
                    req.spec_accepted / req.spec_proposed, self._mtags)
        if self._obs_spans and req.trace is not None:
            from ray_tpu.util import tracing

            off = time.time() - time.monotonic()  # mono -> wall
            if (req.first_token_at is not None
                    and req.finished_at > req.first_token_at):
                tracing.record_span(
                    "decode", req.first_token_at + off,
                    req.finished_at + off, ctx=req.trace,
                    request=req.request_id, tokens=req.generated)
            tracing.record_span(
                "engine-request", req.submitted_at + off,
                req.finished_at + off, ctx=req.trace,
                request=req.request_id, outcome=status,
                tokens=req.generated, preemptions=req.preemptions)

    # -------------------------------------------------------- the loop

    def _admit(self) -> None:
        while self._free and (self._requeue
                              or not self._pending.empty()):
            # Drain up to len(free) pending requests (preempted requeues
            # first — they were admitted before anything still queued),
            # split them into prefix-cache hits and misses, and prefill
            # each group as ONE batched device call per prompt/suffix
            # bucket.
            wave: List[_Request] = []
            while len(wave) < len(self._free):
                if self._requeue:
                    wave.append(self._requeue.pop(0))
                    self._queued_tokens -= len(wave[-1].tokens)
                    continue
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    break
                self._queued_tokens -= len(req.tokens)
                wave.append(req)
            if not wave:
                return
            # Dead-on-arrival requests (cancelled while queued, or
            # deadline already passed) retire HERE — before any prefix
            # match or device work. They never touch the device and the
            # wave refills from the queue behind them.
            live: List[_Request] = []
            now = time.monotonic()
            for req in wave:
                with self._reqs_lock:
                    req.admitted = True
                    if req.cancelled:
                        self._queued_cancelled -= 1
                if req.cancelled:
                    self._retire(req, "cancelled")
                elif req.deadline is not None and now > req.deadline:
                    self._retire(req, "deadline_exceeded")
                else:
                    live.append(req)
            if not live:
                continue
            if self.paged:
                if not self._admit_paged(live):
                    return  # pool dry: stop admitting this step
                continue
            hits: List[_Request] = []
            misses: List[_Request] = []
            for req in live:
                m = (self.prefix.match(req.tokens)
                     if self.prefix is not None else None)
                if m is not None:
                    req.prefix_entry, req.prefix_len = m
                    hits.append(req)
                else:
                    misses.append(req)
            self._mark_admitted(live)
            self._admit_full(misses)
            self._admit_suffix(hits)

    def _admit_paged(self, live: List[_Request]) -> bool:
        """Seat a wave in paged mode: prefix pages splice into the slot's
        block table with ZERO device copies, fresh pages come from the
        allocator, and long prefills hand off to the chunked-prefill
        interleaver instead of running one monolithic program. Returns
        False when the pool ran dry mid-wave (unseated requests are
        pushed back in order; admission pauses until pages free)."""
        chunk = self.prefill_chunk_tokens
        full_group: List[_Request] = []
        suffix_group: List[_Request] = []
        seated: List[_Request] = []
        for i, req in enumerate(live):
            if req.adopt is not None:
                # Disaggregated adopt: the prompt's KV already exists as
                # a handed-off page payload — scatter it in, no model
                # math, no prefix match (the adopted pages ARE the
                # prompt; they get inserted into the prefix index so
                # later prompts can splice them).
                if not self._seat_adopted(req):
                    rest = live[i:]
                    for r in reversed(rest):
                        with self._reqs_lock:
                            r.admitted = False
                        self._requeue.insert(0, r)
                        self._queued_tokens += len(r.tokens)
                    break
                continue
            m = (self.prefix.match(req.tokens)
                 if self.prefix is not None else None)
            if m is not None:
                req.prefix_pages, req.prefix_len = m
            else:
                req.prefix_pages, req.prefix_len = [], 0
            suffix_len = len(req.tokens) - req.prefix_len
            if chunk > 0 and suffix_len > chunk:
                # Chunked prefill: take the slot and the spliced prefix
                # now; _prefill_tick runs the chunks between decode
                # steps (and allocates pages chunk by chunk).
                slot = self._free.pop()
                req.slot = slot  # ownership on the request before any
                #   fallible call: a raise must not strand the lease
                self._set_slot_pages(slot, req.prefix_pages)
                req.prefilled = req.prefix_len
                # Park the device cursor at the spliced length NOW: the
                # slot may sit un-ticked for several steps (one chunk
                # per step, FIFO), and each decode step scribbles its
                # idle-row junk at pos=length — at 0 that would land
                # INSIDE a shared prefix page and corrupt it for every
                # borrower. At prefix_len it lands in the slot's own
                # (or scratch) territory, overwritten by the first
                # chunk's scatter.
                self.cache["length"] = \
                    self.cache["length"].at[slot].set(req.prefix_len)
                self._prefilling[slot] = req
                seated.append(req)
                continue
            need = self._seq_pages(len(req.tokens)) - len(req.prefix_pages)
            pages = self._alloc_pages(need)
            if pages is None:
                # Dry: drop the splice pins, push this and the rest of
                # the wave back (front, original order) and pause.
                self._pages.free(req.prefix_pages)
                req.prefix_pages = []
                req.prefix_len = 0
                rest = live[i:]
                for r in reversed(rest):
                    with self._reqs_lock:
                        r.admitted = False
                    self._requeue.insert(0, r)
                    self._queued_tokens += len(r.tokens)
                break
            slot = self._free.pop()
            req.slot = slot
            self._set_slot_pages(slot, req.prefix_pages + pages)
            seated.append(req)
            (suffix_group if req.prefix_len else full_group).append(req)
        self._mark_admitted(seated)
        self._admit_paged_full(full_group)
        self._admit_paged_suffix(suffix_group)
        return not self._requeue

    def _seat_adopted(self, req: _Request) -> bool:
        """Seat one adopted (handed-off) request: allocate pages for the
        committed prompt, scatter the payload in with the jitted adopt
        program, and emit the handoff's first token — the request enters
        the decode loop exactly as if this engine had prefilled it.
        Returns False when the pool is dry (caller requeues; the adopt
        payload stays on the request for the retry)."""
        import jax.numpy as jnp

        adopt = req.adopt
        clen = int(adopt["committed_len"])
        pages = self._alloc_pages(self._seq_pages(clen))
        if pages is None:
            return False
        slot = self._free.pop()
        req.slot = slot  # ownership on the request before any fallible
        #   call: a raise must not strand the pages
        self._set_slot_pages(slot, pages)
        req.prefix_pages, req.prefix_len = [], 0
        req.prefilled = clen
        # Pow-2 page-count bucket: one compiled adopt program per width,
        # pad columns scatter zero payloads into the scratch page.
        width = 1
        while width < len(pages):
            width *= 2
        ids = np.zeros((width,), np.int32)
        ids[:len(pages)] = pages
        L = self.cache["k"].shape[0]
        tail = self.cache["k"].shape[2:]
        k_pad = np.zeros((L, width) + tuple(tail), adopt["k"].dtype)
        v_pad = np.zeros((L, width) + tuple(tail), adopt["v"].dtype)
        k_pad[:, :len(pages)] = adopt["k"]
        v_pad[:, :len(pages)] = adopt["v"]
        t0 = time.time()
        self.cache = self._dispatch_fresh(
            ("adopt_pages", width),
            lambda: self._adopt_pages(
                self.cache, jnp.asarray(k_pad), jnp.asarray(v_pad),
                jnp.asarray(ids), jnp.asarray([slot], np.int32),
                jnp.asarray([clen], np.int32), width=width))
        self._wave_span("adopt", t0, [req], pages=len(pages))
        if self.steplog.enabled:
            self.steplog.event("handoff-adopt", slot=slot,
                               pages=len(pages), committed=clen)
            self._handoff_phases.append(
                {"phase": "handoff", "t0": t0, "t1": time.time(),
                 "slot": slot, "pages": len(pages)})
        self._mark_admitted([req])
        self._post_adopt(req, slot)
        return True

    def _post_adopt(self, req: _Request, slot: int) -> None:
        """Adopted twin of _post_admit's per-request tail: prefix-index
        insert first (the adopted pages hold the full prompt, so later
        prompts sharing it splice against THIS replica), then emit the
        handoff's first token and enter the decode loop."""
        if self.prefix is not None:
            self.prefix.insert(req.tokens, self._slot_pages[slot],
                               matched_len=0)
        now = time.monotonic()
        self._tokens_dev = None
        tok = int(req.adopt["first_token"])
        req.first_token_at = now
        self._emit(req, tok)
        self._tokens[slot] = tok
        self._active[slot] = req
        self.handoffs_adopted += 1
        req.adopt = None  # drop the multi-MB payload promptly
        if req.generated >= req.max_new_tokens or (
                req.eos_id is not None and tok == req.eos_id):
            self._finish(slot)
        elif self.spec:
            self._draft_seat([req])

    def _admit_paged_full(self, reqs: List[_Request]) -> None:
        import jax.numpy as jnp

        ld = self._ld
        by_bucket: Dict[int, List[_Request]] = {}
        for req in reqs:
            bucket = min(ld.cache_bucket(len(req.tokens),
                                         self.prefill_bucket),
                         self.capacity)
            by_bucket.setdefault(bucket, []).append(req)
        T = self.page_tokens
        for bucket, group in by_bucket.items():
            n = 1
            while n < len(group):
                n *= 2
            wp = max(1, -(-bucket // T))  # bt columns covering the bucket
            rows = np.zeros((n, bucket), np.int32)
            lengths = np.zeros((n,), np.int32)
            slot_ids = np.full((n,), group[-1].slot, np.int32)
            bt = np.zeros((n, wp), np.int32)
            for i, req in enumerate(group):
                rows[i, :len(req.tokens)] = req.tokens
                lengths[i] = len(req.tokens)
                slot_ids[i] = req.slot
                bt[i] = self._block_tables[req.slot, :wp]
            for i in range(len(group), n):  # idempotent pad rows
                rows[i] = rows[len(group) - 1]
                lengths[i] = lengths[len(group) - 1]
                bt[i] = bt[len(group) - 1]
            self._prefill_waves += 1
            t0 = time.time()
            logits, self.cache = self._dispatch_fresh(
                ("paged_prefill", n, bucket),
                lambda: self._paged_prefill(
                    self.params, self.cache, jnp.asarray(rows),
                    jnp.asarray(lengths), jnp.asarray(bt),
                    jnp.asarray(slot_ids), n=n, bucket=bucket))
            logits = np.array(logits)
            self._wave_span("prefill", t0, group, n=len(group),
                            bucket=bucket)
            self._post_admit(group, [r.slot for r in group], logits)

    def _admit_paged_suffix(self, reqs: List[_Request]) -> None:
        """Prefix-hit paged admissions: the shared pages are already in
        the slots' block tables (zero-copy splice at _admit_paged);
        prefill only the uncached suffixes, one program per
        (n, bucket, width) tuple."""
        import jax.numpy as jnp

        ld = self._ld
        T = self.page_tokens
        by_bucket: Dict[int, List[_Request]] = {}
        for req in reqs:
            suffix_len = len(req.tokens) - req.prefix_len
            bucket = min(ld.cache_bucket(suffix_len,
                                         self._suffix_bucket_min),
                         self.capacity)
            by_bucket.setdefault(bucket, []).append(req)
        for bucket, group in by_bucket.items():
            n = 1
            while n < len(group):
                n *= 2
            need = max(-(-(r.prefix_len + bucket) // T) for r in group)
            width = 1
            while width < need:
                width *= 2
            width = min(width, self.slot_pages_max)
            rows = np.zeros((n, bucket), np.int32)
            plens = np.zeros((n,), np.int32)
            lengths = np.zeros((n,), np.int32)
            slot_ids = np.full((n,), group[-1].slot, np.int32)
            bt = np.zeros((n, width), np.int32)
            for i, req in enumerate(group):
                suffix = req.tokens[req.prefix_len:]
                rows[i, :len(suffix)] = suffix
                plens[i] = req.prefix_len
                lengths[i] = len(req.tokens)
                slot_ids[i] = req.slot
                bt[i] = self._block_tables[req.slot, :width]
            for i in range(len(group), n):  # idempotent pad rows
                rows[i] = rows[len(group) - 1]
                plens[i] = plens[len(group) - 1]
                lengths[i] = lengths[len(group) - 1]
                bt[i] = bt[len(group) - 1]
            self._prefill_waves += 1
            t0 = time.time()
            logits, self.cache = self._dispatch_fresh(
                ("paged_suffix", n, bucket, width),
                lambda: self._paged_suffix(
                    self.params, self.cache, jnp.asarray(rows),
                    jnp.asarray(plens), jnp.asarray(lengths),
                    jnp.asarray(bt), jnp.asarray(slot_ids),
                    n=n, bucket=bucket, width=width))
            logits = np.array(logits)
            self._wave_span("suffix-prefill", t0, group, n=len(group),
                            bucket=bucket)
            self._post_admit(group, [r.slot for r in group], logits)

    def _prefill_tick(self) -> None:
        """Chunked-prefill interleaving: advance the OLDEST mid-prefill
        slot by at most ONE ``prefill_chunk_tokens`` chunk, then return
        so the decode step runs. A 4k-token admission thus costs active
        streams one chunk of latency per token, never its whole
        prefill. Page allocation is chunk-by-chunk; a dry pool skips
        the tick (decode keeps draining; the chunk retries next step)."""
        if not self._prefilling:
            return
        import jax.numpy as jnp

        ld = self._ld
        T = self.page_tokens
        slot = min(self._prefilling,
                   key=lambda s: self._prefilling[s].submitted_at)
        req = self._prefilling[slot]
        remaining = len(req.tokens) - req.prefilled
        step_tok = min(self.prefill_chunk_tokens, remaining)
        bucket = min(ld.cache_bucket(step_tok, self._suffix_bucket_min),
                     self.prefill_chunk_tokens)
        need = self._seq_pages(req.prefilled + step_tok) \
            - len(self._slot_pages[slot])
        if need > 0:
            got = self._alloc_pages(need)
            if got is None:
                return
            self._grow_slot(slot, got)
        width = 1
        while width * T < req.prefilled + bucket:
            width *= 2
        width = min(width, self.slot_pages_max)
        rows = np.zeros((1, bucket), np.int32)
        rows[0, :step_tok] = req.tokens[req.prefilled:
                                        req.prefilled + step_tok]
        bt = self._block_tables[slot:slot + 1, :width]
        t0 = time.time()
        logits, self.cache = self._dispatch_fresh(
            ("paged_suffix", 1, bucket, width),
            lambda: self._paged_suffix(
                self.params, self.cache, jnp.asarray(rows),
                jnp.asarray([req.prefilled], np.int32),
                jnp.asarray([req.prefilled + step_tok], np.int32),
                jnp.asarray(bt), jnp.asarray([slot], np.int32),
                n=1, bucket=bucket, width=width))
        self.prefill_chunks += 1
        self._wave_span("prefill-chunk", t0, [req], tokens=step_tok,
                        prefilled=req.prefilled + step_tok,
                        prompt=len(req.tokens))
        req.prefilled += step_tok
        if req.prefilled >= len(req.tokens):
            self._prefilling.pop(slot)
            self._post_admit([req], [slot], np.array(logits))

    def _retire(self, req: _Request, status: str) -> None:
        """Terminal exit for a request that never held a slot."""
        req.status = status
        req.finished_at = time.monotonic()
        if status == "cancelled":
            self.cancelled += 1
        elif status == "deadline_exceeded":
            self.deadline_exceeded += 1
        self._observe_terminal(req, status)
        with self._reqs_lock:
            self._requests.pop(req.request_id, None)
        req.done.set()

    def _purge_pending(self) -> None:
        """Drop dead entries (cancelled / deadline-expired) from the
        pending queue WITHOUT waiting for a slot to free: when every
        slot is busy for minutes, admission never runs, but a cancelled
        caller's entry must still retire promptly — it would otherwise
        hold its done-event, its _requests entry, and (for expiries)
        inflate the load signal. One FIFO-preserving rotation."""
        now = time.monotonic()
        for _ in range(self._pending.qsize()):
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            with self._reqs_lock:
                dead = req.cancelled
                if dead:
                    self._queued_cancelled -= 1
                    req.admitted = True
            if dead:
                self._queued_tokens -= len(req.tokens)
                self._retire(req, "cancelled")
            elif req.deadline is not None and now > req.deadline:
                with self._reqs_lock:
                    req.admitted = True
                self._queued_tokens -= len(req.tokens)
                self._retire(req, "deadline_exceeded")
            else:
                self._pending.put(req)
        # Preempted/pushed-back requests wait in _requeue, not the
        # queue: give their cancels/deadlines the same prompt exit.
        for req in list(self._requeue):
            with self._reqs_lock:
                dead = req.cancelled
                if dead:
                    self._queued_cancelled -= 1
                    req.admitted = True
            expired = (not dead and req.deadline is not None
                       and now > req.deadline)
            if dead or expired:
                self._requeue.remove(req)
                self._queued_tokens -= len(req.tokens)
                if expired:
                    with self._reqs_lock:
                        req.admitted = True
                self._retire(req, "cancelled" if dead
                             else "deadline_exceeded")

    def _admit_full(self, reqs: List[_Request]) -> None:
        import jax.numpy as jnp

        ld = self._ld
        by_bucket: Dict[int, List[_Request]] = {}
        for req in reqs:
            bucket = min(ld.cache_bucket(len(req.tokens),
                                         self.prefill_bucket),
                         self.capacity)
            by_bucket.setdefault(bucket, []).append(req)
        for bucket, group in by_bucket.items():
            slots = [self._free.pop() for _ in group]
            # Pad the admission count to a power of two (bounded
            # program set); pad rows REPEAT the last real row into
            # the same slot — an idempotent overwrite.
            n = 1
            while n < len(group):
                n *= 2
            rows = np.zeros((n, bucket), np.int32)
            lengths = np.zeros((n,), np.int32)
            slot_ids = np.full((n,), slots[-1], np.int32)
            for i, req in enumerate(group):
                rows[i, :len(req.tokens)] = req.tokens
                lengths[i] = len(req.tokens)
                slot_ids[i] = slots[i]
            for i in range(len(group), n):  # idempotent pad rows
                rows[i] = rows[len(group) - 1]
                lengths[i] = lengths[len(group) - 1]
            self._prefill_waves += 1
            t0 = time.time()
            logits, self.cache = self._dispatch_fresh(
                ("prefill", n, bucket),
                lambda: self._prefill_many(
                    self.params, self.cache, jnp.asarray(rows),
                    jnp.asarray(lengths), jnp.asarray(slot_ids),
                    n=n, bucket=bucket))
            logits = np.array(logits)
            self._wave_span("prefill", t0, group, n=len(group),
                            bucket=bucket)
            self._post_admit(group, slots, logits)

    def _admit_suffix(self, reqs: List[_Request]) -> None:
        """Prefix-hit admissions: splice the matched pool entry into each
        request's slot and prefill only the uncached suffix."""
        import jax.numpy as jnp

        ld = self._ld
        by_bucket: Dict[int, List[_Request]] = {}
        for req in reqs:
            suffix_len = len(req.tokens) - req.prefix_len
            bucket = min(ld.cache_bucket(suffix_len,
                                         self._suffix_bucket_min),
                         self.capacity)
            by_bucket.setdefault(bucket, []).append(req)
        for bucket, group in by_bucket.items():
            slots = [self._free.pop() for _ in group]
            n = 1
            while n < len(group):
                n *= 2
            rows = np.zeros((n, bucket), np.int32)
            plens = np.zeros((n,), np.int32)
            lengths = np.zeros((n,), np.int32)
            entries = np.zeros((n,), np.int32)
            slot_ids = np.full((n,), slots[-1], np.int32)
            for i, req in enumerate(group):
                suffix = req.tokens[req.prefix_len:]
                rows[i, :len(suffix)] = suffix
                plens[i] = req.prefix_len
                lengths[i] = len(req.tokens)
                entries[i] = req.prefix_entry
                slot_ids[i] = slots[i]
            for i in range(len(group), n):  # idempotent pad rows
                rows[i] = rows[len(group) - 1]
                plens[i] = plens[len(group) - 1]
                lengths[i] = lengths[len(group) - 1]
                entries[i] = entries[len(group) - 1]
            self._prefill_waves += 1
            t0 = time.time()
            logits, self.cache = self._dispatch_fresh(
                ("suffix", n, bucket),
                lambda: self._prefill_suffix_many(
                    self.params, self.cache, self._pool["k"],
                    self._pool["v"], jnp.asarray(entries),
                    jnp.asarray(slot_ids), jnp.asarray(rows),
                    jnp.asarray(plens), jnp.asarray(lengths),
                    n=n, bucket=bucket))
            logits = np.array(logits)
            self._wave_span("suffix-prefill", t0, group, n=len(group),
                            bucket=bucket)
            for req in group:
                # The splice program holding the entry is dispatched (and
                # device order is program order), so the row may now be
                # recycled without racing the read.
                self.prefix.release(req.prefix_entry)
            self._post_admit(group, slots, logits)

    def _post_admit(self, group: List[_Request], slots: List[int],
                    logits: np.ndarray) -> None:
        # Paged prefix insert runs BEFORE the emit/finish loop: a
        # request that completes on its very first token (max_new=1 /
        # instant EOS) is _finish-ed inside that loop, which FREES its
        # pages — pinning them afterwards would pin recycled (soon
        # overwritten) pages. Inserting first pins the slot's pages
        # while the slot still owns them; _finish then drops only the
        # slot's own references.
        if self.prefix is not None and self.paged:
            for req, slot in zip(group, slots):
                self.prefix.insert(req.tokens, self._slot_pages[slot],
                                   matched_len=req.prefix_len)
        now = time.monotonic()
        self._tokens_dev = None  # host writes below invalidate the
        #   device-resident token vector (sampled-path feedback)
        for i, req in enumerate(group):
            tok = self._sample_host(logits[i], req)
            req.slot = slots[i]
            req.first_token_at = now
            if req.prefill_only:
                # Disaggregated prefill terminal: the deliverable is the
                # slot's filled pages + the sampled first token, not an
                # emitted stream. Gather to host, then finish the slot —
                # its device pages free immediately (the prefix insert
                # above already pinned the shareable ones).
                self._capture_handoff(req, slots[i], tok)
                self._active[slots[i]] = req
                self._finish(slots[i])
                continue
            self._emit(req, tok)
            self._tokens[slots[i]] = tok
            self._active[slots[i]] = req
            if req.generated >= req.max_new_tokens or (
                    req.eos_id is not None and tok == req.eos_id):
                self._finish(slots[i])
        # Contiguous insert stays AFTER: it copies the slot's leading
        # positions into a separate pool row on device, and the rows
        # still hold the full prompt K/V (a _finish only parks
        # ``length``). Pool inserts dedup on the token key either way,
        # and run before any later admission can recycle these slots.
        if self.prefix is not None and not self.paged:
            for req, slot in zip(group, slots):
                ins = self.prefix.insert(req.tokens,
                                         matched_len=req.prefix_len)
                if ins is not None:
                    row, _ins_len = ins
                    self._pool["k"], self._pool["v"] = \
                        self._dispatch_fresh(
                            ("pool_insert",),
                            lambda: self._pool_insert(
                                self.cache, self._pool["k"],
                                self._pool["v"], slot, row))
        if self.spec:
            self._draft_seat([r for r in group if not r.done.is_set()])

    def _capture_handoff(self, req: _Request, slot: int,
                         first_token: int) -> None:
        """Prefill-only terminal: gather the slot's filled pages to host
        as the handoff payload. The gather is a pure device->host copy
        of page payloads (no model math), so an adopting engine's state
        is bit-identical to having prefilled there. The first sampled
        token rides the descriptor instead of being emitted here — the
        decode side emits it, keeping the client-visible stream
        identical to the colocated path."""
        t0 = time.time()
        ids = np.asarray(self._slot_pages[slot], np.int32)
        # np.array (never asarray): the payload outlives later donated
        # dispatches, so it must OWN its bytes — a host view of the
        # cache would be clobbered in place (the PR 16 pin).
        k = np.array(self.cache["k"][:, ids])
        v = np.array(self.cache["v"][:, ids])
        req.handoff = {
            "k": k, "v": v,
            "committed_len": int(req.prompt_len),
            "first_token": int(first_token),
            "page_tokens": self.page_tokens,
            "nbytes": int(k.nbytes + v.nbytes),
        }
        self.handoffs_published += 1
        if self.steplog.enabled:
            self.steplog.event("handoff", slot=slot, pages=len(ids),
                               nbytes=req.handoff["nbytes"])
            self._handoff_phases.append(
                {"phase": "handoff", "t0": t0, "t1": time.time(),
                 "slot": slot, "pages": int(len(ids))})

    def _draft_seat(self, reqs: List[_Request]) -> None:
        """Give each freshly-admitted slot its draft-side state: draft
        pages covering the prompt and a full draft prefill (prefix-hit
        target admissions still draft-prefill the WHOLE prompt — the
        draft pool has no prefix index, and the draft is cheap by
        construction). A slot the draft pool cannot seat even after
        evicting younger draft seats is marked draftless (-1): its spec
        rounds run with junk proposals the verify forward simply
        rejects — correct, just not faster — instead of wedging the
        batch."""
        for req in reqs:
            self._draft_prefill_slot(req.slot, req,
                                     np.asarray(req.tokens, np.int32))

    def _draft_prefill_slot(self, slot: int, req: _Request,
                            seq: np.ndarray) -> bool:
        """Allocate draft pages covering ``seq`` and prefill it into the
        slot's draft state; ``seq`` is the true committed token stream
        (the whole prompt at admission, prompt+output on resync). False
        = slot no longer owns the seat, or pool dry even after evicting
        younger draft seats (slot demoted to draftless)."""
        import jax.numpy as jnp

        if self._active.get(slot) is not req:
            return False  # finished/preempted inside this admission
        got = self._draft_pages.alloc(self._seq_pages(len(seq)))
        while got is None and self._draft_evict_one(slot):
            got = self._draft_pages.alloc(self._seq_pages(len(seq)))
        if got is None:
            self._draft_demote(slot, req)
            return False
        self._draft_bt[slot, :] = 0
        self._draft_bt[slot, :len(got)] = got
        self._draft_slot_pages[slot] = got
        bucket = min(self._ld.cache_bucket(len(seq),
                                           self.prefill_bucket),
                     self.capacity)
        wp = max(1, -(-bucket // self.page_tokens))
        rows = np.zeros((1, bucket), np.int32)
        rows[0, :len(seq)] = seq
        bt = self._draft_bt[slot:slot + 1, :wp]
        t0 = time.time()
        self._draft_cache = self._dispatch_fresh(
            ("draft_prefill", 1, bucket),
            lambda: self._draft_prefill(
                self._draft_params, self._draft_cache,
                jnp.asarray(rows),
                jnp.asarray([len(seq)], np.int32),
                jnp.asarray(bt), jnp.asarray([slot], np.int32),
                n=1, bucket=bucket))
        self._draft_committed[slot] = len(seq)
        self._wave_span("draft-prefill", t0, [req], tokens=len(seq))
        return True

    def _draft_resync(self, slot: int, req: _Request) -> bool:
        """Plain-decode interludes (mixed-temperature batches, chunked
        greedy runs, draftless neighbours) advance the target while the
        draft idles; once the draft is more than one round behind, its
        bounded catch-up row can't close the gap — rebuild the slot's
        draft state with one full draft prefill of the true sequence."""
        L = req.prompt_len + req.generated - 1
        self._draft_pages.free(self._draft_slot_pages[slot])
        self._draft_slot_pages[slot] = []
        self._draft_bt[slot, :] = 0
        seq = np.asarray([self._token_at(req, p) for p in range(L)],
                         np.int32)
        return self._draft_prefill_slot(slot, req, seq)

    @staticmethod
    def _token_at(req: _Request, p: int) -> int:
        """True committed token at absolute position p (prompt, then
        generated output — valid for reabsorbed requests too, whose
        prompt_len stays the ORIGINAL admission length)."""
        return (int(req.tokens[p]) if p < req.prompt_len
                else int(req.output[p - req.prompt_len]))

    def _sample_host(self, logits: np.ndarray, req: _Request) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits / req.temperature
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    _last_cb_log = 0.0  # class-wide rate limit for callback-failure logs

    def _emit(self, req: _Request, tok: int) -> None:
        req.output.append(tok)
        req.generated += 1
        self.tokens_out += 1
        if req.on_token is None:
            return
        try:
            req.on_token(tok)
        except Exception as e:  # noqa: BLE001 — the decode loop must
            # survive a broken streaming consumer, but silently eating
            # the error made streaming failures undiagnosable. Record
            # the FIRST failure on the request and log once per request
            # (rate-limited across requests: a wedged consumer fails on
            # every token of every request).
            if req.on_token_error is None:
                req.on_token_error = f"{type(e).__name__}: {e}"
                now = time.monotonic()
                if now - DecodeEngine._last_cb_log > 1.0:
                    DecodeEngine._last_cb_log = now
                    logger.warning(
                        "on_token callback failed (slot %d, %d tokens "
                        "emitted): %s", req.slot, req.generated,
                        req.on_token_error, exc_info=True)

    def _release_slot(self, slot: int) -> None:
        """Slot teardown shared by _finish and preemption: paged mode
        drops the slot's page references (shared prefix pages survive on
        the index's pins; exclusively-owned pages recycle immediately)
        and parks the block-table row on the scratch page."""
        if self.paged:
            pages = self._slot_pages[slot]
            self._slot_pages[slot] = []
            self._block_tables[slot, :] = 0
            self._pages.free(pages)
            if pages and self.steplog.enabled:
                self.steplog.event("page-free", n=len(pages),
                                   free=self._pages.free_count)
        if self.spec:
            dpages = self._draft_slot_pages[slot]
            self._draft_slot_pages[slot] = []
            self._draft_bt[slot, :] = 0
            self._draft_pages.free(dpages)
            self._draft_committed[slot] = 0
            self._draft_cache["length"] = \
                self._draft_cache["length"].at[slot].set(0)
        self._free.append(slot)
        # Park the freed slot at length 0 so idle slots don't walk their
        # cursor toward the capacity edge while others decode.
        self.cache["length"] = self.cache["length"].at[slot].set(0)
        self._tokens[slot] = 0
        self._tokens_dev = None

    def _finish(self, slot: int, status: str = "completed") -> None:
        req = self._active.pop(slot, None)
        if req is None:
            req = self._prefilling.pop(slot)  # died mid-chunked-prefill
        # Return the slot IMMEDIATELY after the active-pop: _free is only
        # consumed by _admit on this same thread, but stats() reads both
        # cross-thread — a device dispatch between the pop and the append
        # would show active+free < slots (a phantom wedged slot).
        self._release_slot(slot)
        req.status = status
        req.finished_at = time.monotonic()
        if status == "completed":
            # Service-time EMA feeds the shed path's Retry-After estimate.
            service = req.finished_at - req.submitted_at
            self._ema_request_s = (service if self._ema_request_s <= 0
                                   else 0.7 * self._ema_request_s
                                   + 0.3 * service)
        elif status == "cancelled":
            self.cancelled += 1
        elif status == "deadline_exceeded":
            self.deadline_exceeded += 1
        self._observe_terminal(req, status)
        with self._reqs_lock:
            self._requests.pop(req.request_id, None)
        req.done.set()

    def _reap(self) -> None:
        """Free slots whose requests are dead (cancelled, or past their
        deadline): runs at every step boundary, so a dead request costs
        at most ONE more decode step — its slot and its place in the
        batch go back to live traffic immediately (the property Orca-
        style iteration-level scheduling is for)."""
        now = time.monotonic()
        if (self._queued_cancelled > 0
                or (now - self._last_purge > 0.5
                    and (self._requeue or not self._pending.empty()))):
            self._last_purge = now
            self._purge_pending()
        for slot in list(self._active):
            req = self._active[slot]
            if req.cancelled:
                self._finish(slot, "cancelled")
            elif req.deadline is not None and now > req.deadline:
                self._finish(slot, "deadline_exceeded")
        # Mid-chunked-prefill slots die the same way: their pages (all
        # non-shared ones) free within ONE step boundary, like actives.
        for slot in list(self._prefilling):
            req = self._prefilling[slot]
            if req.cancelled:
                self._finish(slot, "cancelled")
            elif req.deadline is not None and now > req.deadline:
                self._finish(slot, "deadline_exceeded")

    def _pick_chunk(self) -> int:
        """Greedy decode steps fusable into one device call right now."""
        # Chunking engages when the batch can't change mid-chunk anyway
        # (no free slot for a pending request) or nothing is waiting —
        # and never while a chunked prefill is mid-flight (the whole
        # point of interleaving is a prefill chunk between EVERY step).
        if (self.decode_chunk > 1
                and (self._pending.empty() or not self._free)
                and not self._requeue and not self._prefilling
                and all(r.temperature <= 0.0
                        for r in self._active.values())):
            chunk = min(self.decode_chunk,
                        min(r.max_new_tokens - r.generated
                            for r in self._active.values()))
            # Round down to a power of two: each distinct k is its own
            # compiled program, so the program set must stay bounded
            # ({1, 2, 4, ..., decode_chunk}), not one per remaining-count.
            while chunk & (chunk - 1):
                chunk &= chunk - 1
            return chunk
        return 1

    def step(self) -> int:
        """Admit pending prefills, run at most one interleaved prefill
        chunk, advance every active slot one token. Returns the number
        of active slots stepped.

        When the step recorder is on (``decode_step_timeline``), the
        step's phases (admission prefills, interleaved prefill chunk,
        decode) land as one ring row with batch occupancy — the "why
        was this token slow" record. Recording costs a few clock reads
        and one deque append per STEP; with the ring off this path is
        byte-identical to the uninstrumented loop."""
        import jax.numpy as jnp

        rec = self.steplog.enabled
        phases: List[Dict[str, Any]] = []
        t_step0 = time.time() if rec else 0.0
        if rec:
            w0 = self._prefill_waves
            c0 = self.prefill_chunks
        self._reap()
        self._admit()
        if rec and self._prefill_waves > w0:
            phases.append({"phase": "admit", "t0": t_step0,
                           "t1": time.time(),
                           "waves": self._prefill_waves - w0})
        if self.paged:
            t0 = time.time() if rec else 0.0
            self._prefill_tick()
            if rec and self.prefill_chunks > c0:
                phases.append({"phase": "prefill_chunk", "t0": t0,
                               "t1": time.time()})
        if not self._active:
            self._steplog_row(t_step0, phases)
            return 0
        if self._spec_ready():
            # Page both pools for the round up front (block tables are
            # static across the draft/verify calls). The target ensure
            # may preempt the youngest request; the draft ensure only
            # ever demotes draft seats.
            self._ensure_decode_pages(self.spec_k + 1)
            if not self._active:
                self._steplog_row(t_step0, phases)
                return 0
            self._ensure_draft_pages(self.spec_k)
            if self._spec_ready():
                return self._spec_step(t_step0, phases, rec)
        chunk = self._pick_chunk()
        if self.paged:
            # Page the next k tokens in BEFORE the program runs: the
            # block tables are static across the call. May preempt the
            # youngest request (and so shrink the active set).
            self._ensure_decode_pages(chunk)
            if not self._active:
                self._steplog_row(t_step0, phases)
                return 0
            chunk = min(chunk, self._pick_chunk())
        stepped = len(self._active)
        if chunk > 1:
            t_d0 = time.time() if rec else 0.0
            if self.paged:
                toks, self.cache = self._dispatch_fresh(
                    ("decode_k", chunk),
                    lambda: self._decode_k(
                        self.params, self.cache,
                        jnp.asarray(self._tokens),
                        jnp.asarray(self._block_tables), k=chunk))
            else:
                toks, self.cache = self._dispatch_fresh(
                    ("decode_k", chunk),
                    lambda: self._decode_k(
                        self.params, self.cache,
                        jnp.asarray(self._tokens), k=chunk))
            toks = np.array(toks)  # (chunk, slots)
            if rec:
                phases.append({"phase": "decode", "t0": t_d0,
                               "t1": time.time(), "batch": stepped,
                               "k": chunk})
            self.steps += chunk
            for slot in list(self._active):
                req = self._active[slot]
                for i in range(chunk):
                    tok = int(toks[i, slot])
                    self._emit(req, tok)
                    self._tokens[slot] = tok
                    if req.generated >= req.max_new_tokens or (
                            req.eos_id is not None
                            and tok == req.eos_id):
                        self._finish(slot)
                        break
            self._steplog_row(t_step0, phases)
            return stepped
        if self._device_sampler:
            return self._sampled_step(t_step0, phases, rec)
        t_d0 = time.time() if rec else 0.0
        if self.paged:
            logits, self.cache = self._dispatch_fresh(
                ("decode",),
                lambda: self._decode(
                    self.params, self.cache, jnp.asarray(self._tokens),
                    jnp.asarray(self._block_tables)))
        else:
            logits, self.cache = self._dispatch_fresh(
                ("decode",),
                lambda: self._decode(
                    self.params, self.cache,
                    jnp.asarray(self._tokens)))
        logits = np.array(logits)
        if rec:
            phases.append({"phase": "decode", "t0": t_d0,
                           "t1": time.time(), "batch": stepped, "k": 1})
        self.steps += 1
        for slot in list(self._active):
            req = self._active[slot]
            tok = self._sample_host(logits[slot], req)
            self._emit(req, tok)
            self._tokens[slot] = tok
            if req.generated >= req.max_new_tokens or (
                    req.eos_id is not None and tok == req.eos_id):
                self._finish(slot)
        self._steplog_row(t_step0, phases)
        return stepped

    def _spec_ready(self) -> bool:
        """Spec rounds engage only when every active request is greedy
        (the acceptance rule compares ARGMAX tokens, which is exactly
        the sequential greedy choice — sampled requests must take the
        plain path, host or device sampler, to keep their RNG stream
        intact) AND at least one slot still holds a draft seat: an
        all-draftless batch would pay the k+1-wide verify forward for
        guaranteed-rejected junk, so it takes the plain path instead."""
        return (self.spec and bool(self._active)
                and all(r.temperature <= 0.0
                        for r in self._active.values())
                and any(self._draft_committed[s] >= 0
                        for s in self._active))

    def _spec_step(self, t_step0: float, phases: List[Dict[str, Any]],
                   rec: bool) -> int:
        """One speculative round: the draft proposes k tokens per active
        slot (catching up on last round's accepted run first), the
        target verifies all k+1 positions in ONE batched forward, the
        longest proposal prefix matching the target's own argmax emits —
        plus the target's correction token — and page cursors roll back
        over the rejected tail. Emits 1..k+1 tokens per slot per round;
        greedy output is bit-identical to sequential decode because
        position j's verify logits condition on exactly the tokens
        sequential decode would have committed whenever proposals 1..j
        all accepted, and nothing past the first mismatch is used."""
        import jax.numpy as jnp

        k = self.spec_k
        stepped = len(self._active)
        # ---- draft: bounded catch-up rows + k proposals per slot
        catchup = np.zeros((self.slots, 2), np.int32)
        clens = np.ones((self.slots,), np.int32)
        for slot, req in list(self._active.items()):
            D = self._draft_committed[slot]
            if D < 0:
                continue  # draftless: junk proposals, still verified
            L = req.prompt_len + req.generated - 1
            if L - D + 1 > 2:
                # _draft_resync may evict younger draft seats or demote
                # this slot to draftless; both leave the round correct,
                # so just re-read the state it settled on.
                if not self._draft_resync(slot, req):
                    continue
                D = self._draft_committed[slot]
            cl = L - D + 1
            for j in range(cl):
                catchup[slot, j] = self._token_at(req, D + j)
            clens[slot] = cl
        t_d0 = time.time() if rec else 0.0
        toks_d, self._draft_cache = self._dispatch_fresh(
            ("spec_draft", k),
            lambda: self._spec_draft(
                self._draft_params, self._draft_cache,
                jnp.asarray(catchup), jnp.asarray(clens),
                jnp.asarray(self._draft_bt), k=k))
        # np.array (never asarray): the next donated dispatch must not
        # clobber an aliased host view of these tokens (PR 14 pin).
        toks_d = np.array(toks_d)                          # (slots, k)
        if rec:
            phases.append({"phase": "draft", "t0": t_d0,
                           "t1": time.time(), "batch": stepped, "k": k})
        # ---- target: verify all k+1 positions in one batched forward
        rows = np.zeros((self.slots, k + 1), np.int32)
        for slot in self._active:
            rows[slot, 0] = self._tokens[slot]
            rows[slot, 1:] = toks_d[slot]
        t_v0 = time.time() if rec else 0.0
        toks_v, self.cache = self._dispatch_fresh(
            ("spec_verify", k),
            lambda: self._spec_verify(
                self.params, self.cache, jnp.asarray(rows),
                jnp.asarray(self._block_tables)))
        toks_v = np.array(toks_v)                          # (slots, k+1)
        # ---- host: longest-matching-prefix acceptance + rollback
        self.steps += 1
        self.spec_rounds += 1
        self._tokens_dev = None
        round_accepted = 0
        upd: List[Tuple[int, int, int]] = []   # (slot, L', D')
        for slot in list(self._active):
            req = self._active[slot]
            g = toks_v[slot]
            n_acc = 0
            while n_acc < k and rows[slot, n_acc + 1] == g[n_acc]:
                n_acc += 1
            if self._draft_committed[slot] >= 0:
                req.spec_proposed += k
                req.spec_accepted += n_acc
                self.spec_proposed += k
                self.spec_accepted += n_acc
                round_accepted += n_acc
            L = req.prompt_len + req.generated - 1
            emitted = 0
            finished = False
            for j in range(n_acc + 1):
                tok = int(g[j])
                self._emit(req, tok)
                self._tokens[slot] = tok
                emitted += 1
                if req.generated >= req.max_new_tokens or (
                        req.eos_id is not None and tok == req.eos_id):
                    finished = True
                    break
            if finished:
                self._finish(slot)  # frees both pools' tails wholesale
                continue
            committed = L + emitted
            if self._draft_committed[slot] >= 0:
                # Draft K/V is valid through L + k (catch-up + its own
                # proposals); past-the-acceptance junk rolls back with
                # the pages below and the next catch-up row rewrites it.
                self._draft_committed[slot] = L + min(emitted, k)
            self._rollback_pages(slot, committed)
            upd.append((slot, committed,
                        max(0, self._draft_committed[slot])))
        if upd:
            ids = jnp.asarray([u[0] for u in upd], jnp.int32)
            self.cache["length"] = self.cache["length"].at[ids].set(
                jnp.asarray([u[1] for u in upd], jnp.int32))
            self._draft_cache["length"] = \
                self._draft_cache["length"].at[ids].set(
                    jnp.asarray([u[2] for u in upd], jnp.int32))
        if rec:
            phases.append({"phase": "verify", "t0": t_v0,
                           "t1": time.time(), "batch": stepped, "k": k,
                           "accepted": round_accepted})
        self._steplog_row(t_step0, phases)
        return stepped

    def _sampled_step(self, t_step0: float,
                      phases: List[Dict[str, Any]], rec: bool) -> int:
        """Single decode step with sampling fused into the device
        program: the (slots, vocab) logits never cross the host
        boundary — only (slots,) token ids do — and consecutive sampled
        steps feed the device-resident token vector straight back in.
        Greedy rows are bit-identical to the host sampler (both argmax
        with first-max tiebreak); sampled rows draw from the program's
        counter-based RNG stream instead of the host generator."""
        import jax.numpy as jnp

        stepped = len(self._active)
        temps = np.zeros((self.slots,), np.float32)
        for slot, req in self._active.items():
            temps[slot] = max(0.0, req.temperature)
        tin = (self._tokens_dev if self._tokens_dev is not None
               else jnp.asarray(self._tokens))
        t_d0 = time.time() if rec else 0.0
        if self.paged:
            toks_dev, self.cache = self._dispatch_fresh(
                ("decode_sampled",),
                lambda: self._decode_sampled(
                    self.params, self.cache, tin,
                    jnp.asarray(self._block_tables), jnp.asarray(temps),
                    jnp.asarray(self.steps, jnp.int32)))
        else:
            toks_dev, self.cache = self._dispatch_fresh(
                ("decode_sampled",),
                lambda: self._decode_sampled(
                    self.params, self.cache, tin, jnp.asarray(temps),
                    jnp.asarray(self.steps, jnp.int32)))
        toks = np.array(toks_dev)  # np.array: next dispatch donates
        self._tokens_dev = toks_dev
        if rec:
            phases.append({"phase": "decode", "t0": t_d0,
                           "t1": time.time(), "batch": stepped, "k": 1,
                           "sampler": "device"})
        self.steps += 1
        for slot in list(self._active):
            req = self._active[slot]
            tok = int(toks[slot])
            self._emit(req, tok)
            self._tokens[slot] = tok
            if req.generated >= req.max_new_tokens or (
                    req.eos_id is not None and tok == req.eos_id):
                self._finish(slot)
        self._steplog_row(t_step0, phases)
        return stepped

    def _steplog_row(self, t0: float, phases: List[Dict[str, Any]]
                     ) -> None:
        """Close the step's timeline row; idle steps with no phases and
        no pending events record nothing (an idle engine must not churn
        useful rows out of the bounded ring)."""
        if self._handoff_phases:
            # Handoff gathers/adopts happen inside admission helpers that
            # don't see the step's phases list; merge them here so the
            # row shows the handoff slice of the step.
            phases = phases + self._handoff_phases
            self._handoff_phases = []
        if not self.steplog.enabled or not (phases
                                            or self.steplog.pending_events):
            return
        self.steplog.record(
            self.steps, t0, time.time(), phases,
            active=len(self._active), prefilling=len(self._prefilling),
            queued=max(0, self._pending.qsize() + len(self._requeue)
                       - self._queued_cancelled),
            pages_free=self._pages.free_count if self.paged else None)

    def warmup(self) -> None:
        """Pre-dispatch the step-loop programs (decode, the chunk grid,
        the fused sampler, the spec round, one admission bucket) so the
        first real request never pays their jit compiles. Safe on an
        idle engine: paged writes route to the scratch page (idle block
        tables are all zeros), contiguous junk lands on idle rows the
        next admission overwrites, and the parked KV lengths are
        restored afterwards. Donated programs take their first dispatch
        HERE through the fresh-compile guard, so the jaxlib 0.4.37
        donated-reload footgun is burned off before traffic."""
        import jax.numpy as jnp

        toks = jnp.asarray(self._tokens)
        zero_t = jnp.zeros((self.slots,), jnp.float32)
        step0 = jnp.asarray(0, jnp.int32)
        if self.paged:
            bt = jnp.asarray(self._block_tables)
            bucket = self.prefill_bucket
            wp = max(1, -(-bucket // self.page_tokens))
            _, self.cache = self._dispatch_fresh(
                ("paged_prefill", 1, bucket),
                lambda: self._paged_prefill(
                    self.params, self.cache,
                    jnp.zeros((1, bucket), jnp.int32),
                    jnp.asarray([0], jnp.int32),
                    jnp.asarray(self._block_tables[:1, :wp]),
                    jnp.asarray([0], jnp.int32), n=1, bucket=bucket))
            _, self.cache = self._dispatch_fresh(
                ("decode",),
                lambda: self._decode(self.params, self.cache, toks, bt))
            c = 2
            while c <= self.decode_chunk:
                _, self.cache = self._dispatch_fresh(
                    ("decode_k", c),
                    lambda: self._decode_k(self.params, self.cache,
                                           toks, bt, k=c))
                c *= 2
            if self._device_sampler:
                _, self.cache = self._dispatch_fresh(
                    ("decode_sampled",),
                    lambda: self._decode_sampled(
                        self.params, self.cache, toks, bt, zero_t,
                        step0))
            if self.spec:
                k = self.spec_k
                _, self._draft_cache = self._dispatch_fresh(
                    ("spec_draft", k),
                    lambda: self._spec_draft(
                        self._draft_params, self._draft_cache,
                        jnp.zeros((self.slots, 2), jnp.int32),
                        jnp.ones((self.slots,), jnp.int32),
                        jnp.asarray(self._draft_bt), k=k))
                _, self.cache = self._dispatch_fresh(
                    ("spec_verify", k),
                    lambda: self._spec_verify(
                        self.params, self.cache,
                        jnp.zeros((self.slots, k + 1), jnp.int32), bt))
                self._draft_cache["length"] = \
                    self._draft_cache["length"].at[:].set(0)
        else:
            _, self.cache = self._dispatch_fresh(
                ("decode",),
                lambda: self._decode(self.params, self.cache, toks))
            c = 2
            while c <= self.decode_chunk:
                _, self.cache = self._dispatch_fresh(
                    ("decode_k", c),
                    lambda: self._decode_k(self.params, self.cache,
                                           toks, k=c))
                c *= 2
            if self._device_sampler:
                _, self.cache = self._dispatch_fresh(
                    ("decode_sampled",),
                    lambda: self._decode_sampled(
                        self.params, self.cache, toks, zero_t, step0))
        self.cache["length"] = self.cache["length"].at[:].set(0)
        self._tokens_dev = None

    def serve_forever(self, idle_wait_s: float = 0.05) -> None:
        """Decode loop for a replica thread: steps while work exists,
        parks on an event while idle."""
        while not self._stop.is_set():
            if (self._active or self._prefilling or self._requeue
                    or not self._pending.empty()):
                self.step()
            else:
                self._work.clear()
                self._work.wait(timeout=idle_wait_s)

    def shutdown(self) -> None:
        self._stop.set()
        self._work.set()

    # ------------------------------------------------------------ stats

    def prefill_backlog_tokens(self) -> int:
        """Prompt tokens accepted but not yet prefilled: the queue (and
        requeue) plus the un-prefilled remainder of mid-chunk slots.
        TTFT debt the autoscaler must see — a replica with two queued
        4k prompts is NOT as loaded as one with two queued 16-token
        prompts, even at equal queue depth."""
        backlog = max(0, self._queued_tokens)
        for req in list(self._prefilling.values()):
            backlog += max(0, len(req.tokens) - req.prefilled)
        return backlog

    def stats(self) -> Dict[str, Any]:
        active = len(self._active)
        prefilling = len(self._prefilling)
        # Live queue depth: cancelled-but-undequeued entries are dead
        # weight, not demand — the autoscaler must not scale out for
        # requests that will be dropped at admission.
        queued = max(0, self._pending.qsize() + len(self._requeue)
                     - self._queued_cancelled)
        backlog = self.prefill_backlog_tokens()
        # Backlog tokens -> load units: one prefill chunk (or one full
        # prefill bucket, unchunked) of pending prompt is about one
        # step's worth of work, i.e. one active-slot-equivalent.
        denom = self.prefill_chunk_tokens or self.prefill_bucket
        out = {
            "steps": self.steps,
            "tokens_out": self.tokens_out,
            # Mesh footprint: chips this engine spans (1 = single-chip).
            # The serve autoscaler divides load by it — a (2, 4) replica
            # is 8 chips of capacity, not one replica-unit.
            "chips": self.mesh.size if self.mesh is not None else 1,
            "mesh_shape": (list(self.mesh.devices.shape)
                           if self.mesh is not None else None),
            "active": active,
            "prefilling": prefilling,
            "slots": self.slots,
            "free_slots": len(self._free),
            "queued": queued,
            "queue_max": self.queue_max,
            # Degradation counters: shed-at-enqueue, cooperative
            # cancellations, deadline expiries, and page-pressure
            # preemptions — surfaced through replica_metrics ->
            # controller snapshot -> serve.status() so overload shows
            # up as it happens.
            "shed": self.shed,
            "cancelled": self.cancelled,
            "deadline_exceeded": self.deadline_exceeded,
            "preempted": self.preempted,
            "prefill_chunks": self.prefill_chunks,
            "prefill_backlog_tokens": backlog,
            # Decode backlog as replica load: occupied slots + pending
            # queue depth + prefill-backlog tokens (in chunk-steps). A
            # full queue behind idle HTTP must read as load to the
            # serve autoscaler, not zero — and neither must a 4k
            # prompt mid-chunked-prefill.
            "load": active + prefilling + queued + backlog // max(1,
                                                                 denom),
        }
        if self.paged:
            out.update(self._pages.stats())
            out["page_tokens"] = self.page_tokens
            out["pages_pinned"] = (self.prefix.pinned_pages
                                   if self.prefix is not None else 0)
            out["kv_fragmentation"] = self._fragmentation()
            out["handoffs_published"] = self.handoffs_published
            out["handoffs_adopted"] = self.handoffs_adopted
        if self.spec:
            # Fleet-visible acceptance: proposed/accepted feed the same
            # counters Prometheus sees; accept_rate is the cumulative
            # ratio (per-request distribution lives in the histogram).
            out["spec"] = {
                "k": self.spec_k,
                "rounds": self.spec_rounds,
                "proposed_tokens": self.spec_proposed,
                "accepted_tokens": self.spec_accepted,
                "accept_rate": (
                    round(self.spec_accepted / self.spec_proposed, 4)
                    if self.spec_proposed else None),
                "draft_pages_total": self._draft_pages.pages,
                "draft_pages_free": self._draft_pages.free_count,
            }
        if self.prefix is not None:
            out["prefix"] = self.prefix.stats()
        if self.steplog.enabled:
            out["step_timeline_rows"] = len(self.steplog._rows)
            out["step_timeline_dropped"] = self.steplog.dropped
        return out

    def set_metrics_deployment(self, name: str) -> None:
        """Re-label this engine's SLO metrics (benches separate their
        warmup/compile phase from the measured phase this way; requests
        observe under the label current at their TERMINAL step)."""
        self._mtags = {"deployment": name}

    def timeline(self) -> Dict[str, Any]:
        """Step-timeline dump + engine identity: the payload behind the
        replica's ``engine_timeline`` RPC and the ``ray_tpu timeline
        --serve`` merge."""
        out = self.steplog.dump()
        out["deployment"] = self._mtags["deployment"]
        out["replica_id"] = self._replica_id
        out["paged"] = self.paged
        out["slots"] = self.slots
        out["spec_k"] = self.spec_k if self.spec else 0
        return out

    def _fragmentation(self) -> float:
        """Internal fragmentation of the page pool: the fraction of
        allocated page-token capacity not backing a live token. Pages
        are interchangeable, so EXTERNAL fragmentation is structurally
        zero — waste is partial tail pages and dead junk, and this is
        the number that says whether page_tokens is sized right."""
        valid: Dict[int, int] = {}
        T = self.page_tokens
        rows = ([(s, r.prompt_len + r.generated)
                 for s, r in list(self._active.items())]
                + [(s, r.prefilled)
                   for s, r in list(self._prefilling.items())])
        for slot, length in rows:
            for i, page in enumerate(self._slot_pages[slot]):
                end = min(T, length - i * T)
                if end > 0:
                    valid[page] = max(valid.get(page, 0), end)
        if self.prefix is not None:
            # Prefix-pinned pages are always full by construction.
            for page in self.prefix.pinned_page_ids():
                valid[page] = T
        in_use = self._pages.in_use
        if not in_use:
            return 0.0
        used_tokens = sum(valid.values())
        return round(max(0.0, 1.0 - used_tokens / (in_use * T)), 4)


class LlamaDecodeDeployment:
    """Serve deployment wrapping a DecodeEngine: POST {"tokens": [...],
    "max_new_tokens": N} -> {"tokens": [...]} with streaming support
    (generator handle path). Replica-per-chip: schedule with
    ``ray_actor_options={"resources": {"TPU": 1}}``."""

    def __init__(self, preset: str = "debug", slots: int = 4,
                 capacity: int = 1024, seed: int = 0,
                 config=None, decode_chunk: int = 1,
                 prefix_pool_entries: Optional[int] = None,
                 prefix_capacity: Optional[int] = None,
                 prefix_match_min_tokens: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 kv_page_tokens: Optional[int] = None,
                 kv_pool_pages: Optional[int] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 mesh_shape=None,
                 spec_draft_model: Optional[str] = None,
                 spec_k: Optional[int] = None,
                 spec_draft_pool_pages: Optional[int] = None,
                 device_sampler: Optional[bool] = None,
                 warmup: Optional[bool] = None):
        import jax

        from ray_tpu.core.config import config as rt_config
        from ray_tpu.models import llama

        cfg = config or llama.PRESETS[preset]
        self.cfg = cfg
        self._sub_slice: Optional[Dict[str, Any]] = None
        params = llama.init_params(cfg, jax.random.key(seed))
        # Draft model for speculative decoding: a (smaller) preset named
        # by knob. Seeded independently of the target — the contract
        # never depends on draft quality, only on verification.
        draft_name = (rt_config.spec_draft_model
                      if spec_draft_model is None else spec_draft_model)
        sk = rt_config.spec_k if spec_k is None else int(spec_k)
        draft_params = draft_cfg = None
        if draft_name and sk > 0:
            draft_cfg = llama.PRESETS[draft_name]
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"spec_draft_model {draft_name!r} vocab "
                    f"({draft_cfg.vocab_size}) != target vocab "
                    f"({cfg.vocab_size}) — proposals must share the "
                    f"token space the target verifies")
            draft_params = llama.init_params(draft_cfg,
                                             jax.random.key(seed + 1))
        self.engine = DecodeEngine(
            params, cfg, slots=slots, capacity=capacity,
            decode_chunk=decode_chunk,
            prefix_pool_entries=prefix_pool_entries,
            prefix_capacity=prefix_capacity,
            prefix_match_min_tokens=prefix_match_min_tokens,
            queue_max=queue_max,
            page_tokens=kv_page_tokens, pool_pages=kv_pool_pages,
            prefill_chunk_tokens=prefill_chunk_tokens,
            mesh_shape=mesh_shape,
            spec_draft_params=draft_params, spec_draft_config=draft_cfg,
            spec_k=sk if draft_params is not None else 0,
            spec_draft_pool_pages=spec_draft_pool_pages,
            device_sampler=device_sampler)
        if (rt_config.decode_warmup if warmup is None else warmup):
            self.engine.warmup()
        # Prefill->decode handoff lease ledger (disaggregated serving):
        # tracks published-but-undischarged KV-page handoffs so the TTL
        # sweep (riding replica_metrics) can return refs nobody claimed.
        from ray_tpu.serve.handoff import HandoffLedger

        self._handoffs = HandoffLedger()
        self._thread = threading.Thread(target=self.engine.serve_forever,
                                        name="decode-loop", daemon=True)
        self._thread.start()

    def set_topology(self, assignment: Dict[str, Any]) -> None:
        """Sub-slice assignment pushed by the serve controller after it
        reserved this replica's chips: advisory on the virtual CPU mesh
        (the process's devices ARE the slice), the device-selection
        input on real multi-host slices. Reported back through
        ``replica_metrics`` so status/routing see where the replica
        lives."""
        self._sub_slice = dict(assignment)

    def replica_metrics(self) -> Dict[str, Any]:
        """Replica-reported load + prefix residency + degradation
        counters, merged into ``ReplicaActor.stats()``: the autoscaler
        scales on decode backlog, the router steers shared prefixes to
        the replica already holding them, and ``serve.status()`` shows
        shedding/cancellation/deadline counts as they happen."""
        s = self.engine.stats()
        out: Dict[str, Any] = {"load": s["load"], "queued": s["queued"],
                               "shed": s["shed"],
                               "cancelled": s["cancelled"],
                               "deadline_exceeded": s["deadline_exceeded"],
                               "prefill_backlog_tokens":
                               s["prefill_backlog_tokens"],
                               "chips": s["chips"],
                               "mesh_shape": s["mesh_shape"]}
        sub = getattr(self, "_sub_slice", None)  # tests build bare
        #   instances around an engine without running __init__
        if sub is not None:
            out["sub_slice"] = dict(sub)
            out["slice_id"] = sub.get("slice_id")
        if self.engine.paged:
            # Page-pool health, controller-aggregated into
            # serve.status(): free/pinned pages and fragmentation say
            # whether the replica can admit, what the prefix cache
            # holds, and whether page_tokens is sized right.
            for key in ("pages_total", "pages_free", "pages_in_use",
                        "pages_pinned", "kv_fragmentation", "preempted"):
                out[key] = s[key]
        if self.engine.spec:
            out["spec"] = s["spec"]
        if self.engine.prefix is not None:
            out["prefix"] = s.get("prefix", {})
            out["prefixes"] = self.engine.prefix.hashes()
        ledger = getattr(self, "_handoffs", None)
        if ledger is not None:
            # The controller's reconcile stats pull doubles as the
            # handoff-lease backstop: expire entries nobody discharged
            # (router death mid-splice) and free their refs.
            self._sweep_handoffs()
            out["handoffs_live"] = ledger.live()
            out["handoff_live_bytes"] = ledger.live_bytes()
            out["handoffs_published"] = s.get("handoffs_published", 0)
            out["handoffs_adopted"] = s.get("handoffs_adopted", 0)
        return out

    def timeline(self) -> Dict[str, Any]:
        """Engine step-timeline dump (ReplicaActor.engine_timeline
        forwards here; merged into the serve Chrome trace)."""
        return self.engine.timeline()

    def _submit(self, request: Dict[str, Any], on_token=None,
                prefill_only: bool = False,
                adopt: Optional[Dict[str, Any]] = None) -> _Request:
        """Admission with the request's deadline attached: explicit
        ``deadline_s`` in the payload wins, else the deadline the serve
        stack propagated with this call (proxy header / handle
        timeout_s / ``serve_request_timeout_s``)."""
        from ray_tpu.serve.replica import request_deadline_s

        deadline_s = request.get("deadline_s")
        if deadline_s is None:
            deadline_s = request_deadline_s()
        return self.engine.submit(
            request["tokens"],
            max_new_tokens=int(request.get("max_new_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"),
            on_token=on_token,
            deadline_s=deadline_s,
            request_id=request.get("request_id"),
            prefill_only=prefill_only,
            adopt=adopt)

    def _wait_done(self, req: _Request) -> None:
        """Block until the engine finishes the request; a wedged decode
        loop (never-completing wait) turns into a cancel + deadline
        error rather than hanging the replica thread forever."""
        if req.deadline is not None:
            # The engine enforces the deadline; the +10 s slack only
            # covers a wedged decode loop (never-completing wait).
            if not req.done.wait(
                    max(0.1, req.deadline - time.monotonic()) + 10.0):
                self.engine.cancel(req.request_id)
                raise DeadlineExceededError(
                    f"request {req.request_id} not finished by the decode "
                    f"loop within its deadline")
        else:
            req.done.wait()

    def __call__(self, request: Dict[str, Any]):
        if request.get("stream"):
            # Generator return = the replica streams it (handle.stream /
            # HTTP chunked via X-Serve-Stream on this same route).
            return self.stream(request)
        req = self._submit(request)
        self._wait_done(req)
        req.raise_for_status()
        return {"tokens": req.output,
                "ttft_s": round(req.first_token_at - req.submitted_at, 4)}

    # --------------------------------------- disaggregated prefill/decode

    def prefill_handoff(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Disagg prefill half: run admission + (chunked) prefill into
        this engine's paged pool, then publish the filled KV pages as
        object-plane refs plus a descriptor small enough to ride the
        router splice inline (budget: ``HANDOFF_DESC_BYTE_BUDGET``).

        The returned descriptor is a LEASE: the caller must either
        adopt-ack it (``discharge_handoff``) or abort it
        (``abort_handoff``) on every path; the ledger's TTL sweep is
        the backstop for a caller that died mid-splice, and a SIGKILL
        of this replica frees the refs structurally (objects die with
        their owner process)."""
        import uuid as _uuid

        import ray_tpu

        req = self._submit(request, prefill_only=True)
        self._wait_done(req)
        req.raise_for_status()
        payload = req.handoff
        if payload is None:  # engine retired the request pre-capture
            raise RuntimeError(
                f"prefill request {req.request_id} completed without a "
                f"handoff payload")
        desc = {
            "handoff_id": _uuid.uuid4().hex[:16],
            "k_ref": ray_tpu.put(payload["k"]),
            "v_ref": ray_tpu.put(payload["v"]),
            "committed_len": payload["committed_len"],
            "first_token": payload["first_token"],
            "page_tokens": payload["page_tokens"],
            "nbytes": payload["nbytes"],
            "prefill_ttft_s": round(
                req.first_token_at - req.submitted_at, 4),
        }
        req.handoff = None  # the object store owns the payload now
        self._handoffs.publish_handoff(desc)
        try:
            self._observe_handoff_published(desc)
        except BaseException:
            # The lease must not outlive a failed publish tail: hand the
            # refs back before the error escapes (graftlint polices the
            # publish->discharge pairing on every raise exit).
            self._drop_handoff(desc["handoff_id"], "aborted")
            raise
        return desc

    def discharge_handoff(self, handoff_id: str) -> None:
        """Adopt-ack from the router splice: the decode replica fetched
        the page payload, so free the refs NOW (one engine step), not
        at the distributed ref tracker's grace sweep."""
        self._drop_handoff(handoff_id, "adopted")

    def abort_handoff(self, handoff_id: str) -> None:
        """Splice failure (decode replica died / cannot adopt / request
        cancelled): return the pages now. Idempotent, like discharge."""
        self._drop_handoff(handoff_id, "aborted")

    def _drop_handoff(self, handoff_id: str, event: str) -> None:
        """Discharge one published handoff and free its payload refs
        eagerly. Idempotent — the router's abort path and the TTL sweep
        may race, and the ledger referees the double discharge."""
        entry = self._handoffs.discharge_handoff(handoff_id)
        if entry is not None:
            self._discharge_entry(entry, event)

    def _sweep_handoffs(self) -> None:
        for entry in self._handoffs.sweep():
            self._discharge_entry(entry, "expired")

    def _discharge_entry(self, entry: Dict[str, Any],
                         event: str) -> None:
        import ray_tpu

        desc = entry["desc"]
        try:
            ray_tpu.free([desc.get("k_ref"), desc.get("v_ref")])
        except Exception:
            logger.warning("freeing handoff %s refs failed",
                           desc.get("handoff_id"), exc_info=True)
        if self.engine._obs_metrics:
            from ray_tpu.serve import metrics as smetrics

            tags = dict(self.engine._mtags)
            smetrics.HANDOFFS.inc(1.0, {**tags, "event": event})
            if event == "adopted":
                # publish->adopt latency: the window the pages spent as
                # host blobs between the two fleets.
                smetrics.HANDOFF_LATENCY.observe(entry["age_s"], tags)

    def _observe_handoff_published(self, desc: Dict[str, Any]) -> None:
        if not self.engine._obs_metrics:
            return
        from ray_tpu.serve import handoff as _handoff
        from ray_tpu.serve import metrics as smetrics

        tags = dict(self.engine._mtags)
        smetrics.HANDOFF_BYTES.observe(
            float(_handoff.descriptor_nbytes(desc)), tags)
        smetrics.HANDOFFS.inc(1.0, {**tags, "event": "published"})

    def _fetch_adopt(self, desc: Dict[str, Any]) -> Dict[str, Any]:
        """Pull the handed-off page payload out of the object plane and
        shape it as the engine's adopt argument. A dead prefill replica
        (refs died with their owner) surfaces as the typed adopt error
        the router maps to re-prefill."""
        import ray_tpu
        from ray_tpu.core.errors import HandoffAdoptError
        from ray_tpu.serve.replica import request_deadline_s

        timeout = request_deadline_s() or 30.0
        try:
            k, v = ray_tpu.get([desc["k_ref"], desc["v_ref"]],
                               timeout=max(1.0, timeout))
        except Exception as e:
            raise HandoffAdoptError(
                f"handoff {desc.get('handoff_id')} page payload "
                f"unavailable: {e!r}") from e
        return {"k": k, "v": v,
                "committed_len": desc["committed_len"],
                "first_token": desc["first_token"],
                "page_tokens": desc["page_tokens"]}

    def decode_adopted(self, request: Dict[str, Any],
                       desc: Dict[str, Any]) -> Dict[str, Any]:
        """Disagg decode half (unary): adopt the published pages into
        this engine's pool — zero recompute — and decode to completion.
        The prompt's KV never transits Python bytes-concat: page blobs
        go object-store -> scatter program -> pool."""
        req = self._submit(request, adopt=self._fetch_adopt(desc))
        self._wait_done(req)
        req.raise_for_status()
        return {"tokens": req.output,
                "ttft_s": desc.get("prefill_ttft_s", 0.0)}

    def stream_adopted(self, request: Dict[str, Any],
                       desc: Dict[str, Any]):
        """Streaming twin of ``decode_adopted``. Adoption (object-plane
        fetch + engine submit) runs EAGERLY in this call, not in the
        returned generator, so the replica's synchronous ``start_stream``
        surfaces adopt failures as retryable call errors and the router
        can discharge the prefill lease the moment the stream id comes
        back."""
        q: "queue.Queue" = queue.Queue()
        req = self._submit(request, on_token=q.put,
                           adopt=self._fetch_adopt(desc))

        def _gen():
            try:
                while True:
                    try:
                        yield q.get(timeout=0.5)
                        continue
                    except queue.Empty:
                        pass
                    if req.done.is_set():
                        while not q.empty():
                            yield q.get()
                        req.raise_for_status()
                        break
            finally:
                if not req.done.is_set():
                    self.engine.cancel(req.request_id)

        return _gen()

    def stream(self, request: Dict[str, Any]):
        """Streaming generator: yields tokens as the engine emits them
        (drive via a streaming handle / HTTP chunked response). Closing
        the generator (client disconnect anywhere up the stack) cancels
        the engine request: the slot frees at the next step and queued-
        but-unadmitted requests never touch the device."""
        q: "queue.Queue" = queue.Queue()
        req = self._submit(request, on_token=q.put)
        try:
            while True:
                try:
                    yield q.get(timeout=0.5)
                    continue
                except queue.Empty:
                    pass
                if req.done.is_set():
                    while not q.empty():
                        yield q.get()
                    # A mid-stream deadline/cancel surfaces as the typed
                    # error instead of silently truncating the stream.
                    req.raise_for_status()
                    break
        finally:
            if not req.done.is_set():
                self.engine.cancel(req.request_id)

    def health(self) -> Dict[str, Any]:
        return self.engine.stats()
