"""serve.run / status / delete / HTTP proxy.

Analogue of the reference's ``serve.run`` + proxy (``serve/api.py``,
``serve/_private/proxy.py:761,1130``). All control-plane state lives in the
ServeController ACTOR (``controller.py``) — this module is a thin client, so
deployments survive the driver that created them; a later driver resolves
the controller by name and keeps operating the same apps.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.core import serialization
from ray_tpu.serve.controller import get_or_create_controller
from ray_tpu.serve.deployment import Deployment, DeploymentHandle, _Router

_http_server: Optional[ThreadingHTTPServer] = None


def run(app: Deployment, name: Optional[str] = None,
        route_prefix: Optional[str] = None,
        ready_timeout_s: float = 60.0) -> DeploymentHandle:
    """Deploy (or redeploy) an application; returns its handle."""
    from ray_tpu import usage as _usage

    _usage.record_feature("serve.run")
    name = name or app.name
    controller = get_or_create_controller()
    version = ray_tpu.get(controller.deploy.remote(
        name, serialization.dumps_function(app.cls), app._init_args,
        app._init_kwargs, app.config_dict()), timeout=ready_timeout_s)
    # HTTP route: explicit prefix, or /<name> by default. Stored on the
    # controller so proxies in ANY process resolve it.
    ray_tpu.get(controller.set_route.remote(
        route_prefix or f"/{name}", name), timeout=30.0)
    handle = DeploymentHandle(name)
    router = _Router.get(name)
    if version is not None:
        router.wait_version(version, ready_timeout_s)
    else:
        router.wait_ready(ready_timeout_s)
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def status(timeout: float = 30.0) -> Dict[str, Any]:
    controller = get_or_create_controller()
    return ray_tpu.get(controller.status.remote(), timeout=timeout)


def delete(name: str, timeout: float = 30.0) -> None:
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete.remote(name), timeout=timeout)


def shutdown(drain_timeout_s: float = 10.0) -> None:
    """Tear down all deployments AND the controller actor. The HTTP proxy
    drains FIRST (stop accepting, let in-flight requests finish against
    still-live replicas — reference: proxy draining on serve shutdown)."""
    stop_http(drain_timeout_s)
    try:
        controller = get_or_create_controller()
        ray_tpu.get(controller.shutdown.remote(), timeout=30.0)
        ray_tpu.kill(controller)
    except Exception:
        pass
    _Router.reset_all()


def _resolve_route(path: str) -> Optional[str]:
    """Longest-prefix route lookup against the controller's route table
    (cached briefly; the proxy may live in any process)."""
    global _routes_cache
    now = time.monotonic()
    if _routes_cache is None or now - _routes_cache[0] > 2.0:
        try:
            controller = get_or_create_controller()
            routes = ray_tpu.get(controller.get_routes.remote(),
                                 timeout=10.0)
            _routes_cache = (now, routes)
        except Exception:
            routes = {} if _routes_cache is None else _routes_cache[1]
    else:
        routes = _routes_cache[1]
    path = "/" + path.strip("/")
    best = None
    for prefix, name in routes.items():
        if (prefix == "/" or path == prefix
                or path.startswith(prefix + "/")):
            if best is None or len(prefix) > len(best[0]):
                best = (prefix, name)
    return best[1] if best else None


_routes_cache = None


class _InFlight:
    """Proxy request accounting for graceful draining."""

    def __init__(self):
        self.count = 0
        self.cond = threading.Condition()

    def __enter__(self):
        with self.cond:
            self.count += 1
        return self

    def __exit__(self, *exc):
        with self.cond:
            self.count -= 1
            self.cond.notify_all()

    def drain(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self.cond:
            while self.count > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self.cond.wait(min(remaining, 1.0))
        return True


_in_flight = _InFlight()
_STREAM_END = object()


class _ProxyHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"  # chunked transfer needs 1.1

    def do_POST(self):  # noqa: N802 (stdlib API)
        with _in_flight:
            self._handle()

    def _handle(self) -> None:
        parts = self.path.strip("/").split("/")
        # Route table first (supports custom route_prefix); fall back to
        # the first path segment as the app name.
        name = _resolve_route(self.path) or parts[0]
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b"null"
        model_id = self.headers.get("serve_multiplexed_model_id", "")
        streaming = (self.headers.get("x-serve-stream", "")
                     or self.headers.get("X-Serve-Stream", ""))
        try:
            payload = json.loads(body)
            handle = DeploymentHandle(name, multiplexed_model_id=model_id)
            if streaming:
                self._stream_response(handle, payload, name)
                return
            result = handle.remote(payload).result(timeout=70)
            data = json.dumps(result).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        except KeyError:
            self.send_error(404, f"no deployment {name!r}")
        except Exception as e:  # noqa: BLE001
            self.send_error(500, str(e))

    def _stream_response(self, handle, payload, name: str) -> None:
        """Chunked transfer encoding, one JSON line per yielded item
        (reference: proxy.py streaming/chunked responses). The generator
        is pulled incrementally — chunks reach the client as the replica
        produces them.

        Errors BEFORE the first item become real HTTP errors (the
        generator is primed before any header ships); a mid-stream error
        can't rewrite the status line, so it becomes an error record in
        the stream and the connection closes (never a second response on
        a keep-alive socket)."""
        stream = handle.stream(payload)
        try:
            first = next(stream, _STREAM_END)
        except KeyError:
            self.send_error(404, f"no deployment {name!r}")
            return
        except Exception as e:  # noqa: BLE001
            self.send_error(500, str(e))
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonlines")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Connection", "close")
        self.end_headers()

        def chunk(data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode())
            self.wfile.write(data + b"\r\n")

        try:
            if first is not _STREAM_END:
                chunk(json.dumps(first).encode() + b"\n")
                for item in stream:
                    chunk(json.dumps(item).encode() + b"\n")
        except Exception as e:  # noqa: BLE001 — headers already sent
            chunk(json.dumps(
                {"__serve_stream_error__": str(e)}).encode() + b"\n")
        finally:
            self.wfile.write(b"0\r\n\r\n")
            self.close_connection = True

    def log_message(self, *args):  # silence
        pass


def start_http(host: str = "127.0.0.1", port: int = 0) -> tuple:
    """Start the HTTP proxy; returns (host, port)."""
    global _http_server
    _http_server = ThreadingHTTPServer((host, port), _ProxyHandler)
    threading.Thread(target=_http_server.serve_forever, name="serve-http",
                     daemon=True).start()
    return _http_server.server_address


def stop_http(drain_timeout_s: float = 10.0) -> None:
    """Stop accepting, then wait for in-flight requests to finish."""
    global _http_server
    if _http_server is None:
        return
    _http_server.shutdown()  # accept loop stops; handler threads continue
    _in_flight.drain(drain_timeout_s)
    _http_server.server_close()
    _http_server = None
