"""serve.run / status / delete / HTTP proxy.

Analogue of the reference's ``serve.run`` + proxy (``serve/api.py``,
``serve/_private/proxy.py:761,1130``). All control-plane state lives in the
ServeController ACTOR (``controller.py``) — this module is a thin client, so
deployments survive the driver that created them; a later driver resolves
the controller by name and keeps operating the same apps.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.core import serialization
from ray_tpu.serve.controller import get_or_create_controller
from ray_tpu.serve.deployment import Deployment, DeploymentHandle, _Router

_http_server: Optional[ThreadingHTTPServer] = None


def run(app: Deployment, name: Optional[str] = None,
        route_prefix: Optional[str] = None,
        ready_timeout_s: float = 60.0) -> DeploymentHandle:
    """Deploy (or redeploy) an application; returns its handle."""
    from ray_tpu import usage as _usage

    _usage.record_feature("serve.run")
    name = name or app.name
    controller = get_or_create_controller()
    version = ray_tpu.get(controller.deploy.remote(
        name, serialization.dumps_function(app.cls), app._init_args,
        app._init_kwargs, app.config_dict()), timeout=ready_timeout_s)
    # HTTP route: explicit prefix, or /<name> by default. Stored on the
    # controller so proxies in ANY process resolve it.
    ray_tpu.get(controller.set_route.remote(
        route_prefix or f"/{name}", name), timeout=30.0)
    handle = DeploymentHandle(name)
    router = _Router.get(name)
    if version is not None:
        router.wait_version(version, ready_timeout_s)
    else:
        router.wait_ready(ready_timeout_s)
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def status(timeout: float = 30.0) -> Dict[str, Any]:
    controller = get_or_create_controller()
    return ray_tpu.get(controller.status.remote(), timeout=timeout)


def delete(name: str, timeout: float = 30.0) -> None:
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete.remote(name), timeout=timeout)


def shutdown() -> None:
    """Tear down all deployments AND the controller actor."""
    global _http_server
    try:
        controller = get_or_create_controller()
        ray_tpu.get(controller.shutdown.remote(), timeout=30.0)
        ray_tpu.kill(controller)
    except Exception:
        pass
    _Router.reset_all()
    if _http_server is not None:
        _http_server.shutdown()
        _http_server = None


def _resolve_route(path: str) -> Optional[str]:
    """Longest-prefix route lookup against the controller's route table
    (cached briefly; the proxy may live in any process)."""
    global _routes_cache
    now = time.monotonic()
    if _routes_cache is None or now - _routes_cache[0] > 2.0:
        try:
            controller = get_or_create_controller()
            routes = ray_tpu.get(controller.get_routes.remote(),
                                 timeout=10.0)
            _routes_cache = (now, routes)
        except Exception:
            routes = {} if _routes_cache is None else _routes_cache[1]
    else:
        routes = _routes_cache[1]
    path = "/" + path.strip("/")
    best = None
    for prefix, name in routes.items():
        if (prefix == "/" or path == prefix
                or path.startswith(prefix + "/")):
            if best is None or len(prefix) > len(best[0]):
                best = (prefix, name)
    return best[1] if best else None


_routes_cache = None


class _ProxyHandler(BaseHTTPRequestHandler):
    def do_POST(self):  # noqa: N802 (stdlib API)
        parts = self.path.strip("/").split("/")
        # Route table first (supports custom route_prefix); fall back to
        # the first path segment as the app name.
        name = _resolve_route(self.path) or parts[0]
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b"null"
        model_id = self.headers.get("serve_multiplexed_model_id", "")
        try:
            payload = json.loads(body)
            handle = DeploymentHandle(name, multiplexed_model_id=model_id)
            result = handle.remote(payload).result(timeout=70)
            data = json.dumps(result).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        except KeyError:
            self.send_error(404, f"no deployment {name!r}")
        except Exception as e:  # noqa: BLE001
            self.send_error(500, str(e))

    def log_message(self, *args):  # silence
        pass


def start_http(host: str = "127.0.0.1", port: int = 0) -> tuple:
    """Start the HTTP proxy; returns (host, port)."""
    global _http_server
    _http_server = ThreadingHTTPServer((host, port), _ProxyHandler)
    threading.Thread(target=_http_server.serve_forever, name="serve-http",
                     daemon=True).start()
    return _http_server.server_address
