"""serve.run / status / delete + HTTP ingress management.

Analogue of the reference's ``serve.run`` (``serve/api.py``). All
control-plane state lives in the ServeController ACTOR (``controller.py``)
— this module is a thin client, so deployments survive the driver that
created them; a later driver resolves the controller by name and keeps
operating the same apps. The HTTP data plane is per-node ProxyActors
supervised by that controller (``proxy.py``; reference:
``serve/_private/proxy.py:131``, ``proxy_state.py``) — NOT a server in the
driver process, so ingress survives driver exit too.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import ray_tpu
from ray_tpu.core import serialization
from ray_tpu.serve.controller import get_or_create_controller
from ray_tpu.serve.deployment import Deployment, DeploymentHandle, _Router


def run(app: Deployment, name: Optional[str] = None,
        route_prefix: Optional[str] = None,
        ready_timeout_s: float = 60.0) -> DeploymentHandle:
    """Deploy (or redeploy) an application; returns its handle."""
    from ray_tpu import usage as _usage

    _usage.record_feature("serve.run")
    name = name or app.name
    controller = get_or_create_controller()
    version = ray_tpu.get(controller.deploy.remote(
        name, serialization.dumps_function(app.cls), app._init_args,
        app._init_kwargs, app.config_dict()), timeout=ready_timeout_s)
    # HTTP route: explicit prefix, or /<name> by default. Stored on the
    # controller so proxies on ANY node resolve it.
    ray_tpu.get(controller.set_route.remote(
        route_prefix or f"/{name}", name), timeout=30.0)
    handle = DeploymentHandle(name)
    router = _Router.get(name)
    if version is not None:
        router.wait_version(version, ready_timeout_s)
    else:
        router.wait_ready(ready_timeout_s)
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def _controller_alive(handle) -> bool:
    """Cheap actor-table read: is the serve controller's record ALIVE
    right now? (RESTARTING/DEAD callers should degrade immediately
    instead of parking a blocking call against the restart.)"""
    try:
        from ray_tpu.core.rpc_stubs import ControllerStub
        from ray_tpu.core.runtime import get_core_worker

        rec = ControllerStub(get_core_worker().controller).get_actor(
            handle.actor_id.binary(), timeout=5.0)
        return rec is not None and rec["state"] == "ALIVE"
    except Exception:
        return False


def _degraded_status() -> Dict[str, Any]:
    """The cached view this process's routers hold: what ``status``
    degrades to while the serve controller is down or restarting. Every
    entry carries ``degraded: True`` so callers can tell a cached
    replica count from a reconciled one."""
    from ray_tpu.serve.deployment import _Router

    with _Router._instances_lock:
        routers = dict(_Router._instances)
    out: Dict[str, Any] = {}
    for name, router in routers.items():
        with router._lock:
            out[name] = {
                "replicas": len(router._replicas),
                "replica_ids": [r["id"] for r in router._replicas],
                "degraded": True,
            }
    return out


def status(timeout: float = 30.0, include_slo: bool = True
           ) -> Dict[str, Any]:
    """Per-deployment control-plane state — replica count, autoscale
    load, page-pool health, disaggregation posture (``role``,
    ``decode_deployment``, live handoff leases ``handoffs_live`` /
    ``handoff_live_bytes``) — plus (``include_slo``) the SLO
    DISTRIBUTIONS from the metrics pipeline: each deployment gains
    an ``slo`` dict with TTFT / inter-token / queue-wait / HTTP-latency
    / handoff histogram summaries (count, mean, p50, p99), outcome
    counters and handoff lease-event counters — the same numbers the
    dashboard serve panel and the proxy's ``/metrics`` route report,
    because all three read the controller's aggregated registry
    through ``serve.metrics.slo_summary``.

    FAILS SOFT during a controller outage: when the controller actor is
    dead or restarting, the call returns this process's cached routing
    view (entries marked ``degraded: True``) instead of raising — the
    observing path must not be the thing that breaks first during the
    exact failure it is observing. The failed probe doubles as the
    failure report that triggers the controller's restart."""
    from ray_tpu.serve.controller import CONTROLLER_NAME
    from ray_tpu.util.deadline import Deadline

    # ``timeout`` is the budget for the WHOLE probe, not per attempt:
    # the retry below runs on the REMAINING time, so a controller that
    # burned the first attempt to its deadline degrades immediately
    # instead of earning a second full allowance.
    dl = Deadline.after(timeout)
    try:
        # Lookup, not get_or_create: a status probe must neither SPAWN
        # a control plane nor block a long ping against a restarting
        # one — the degraded view answers immediately either way.
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        if not _controller_alive(controller):
            return _degraded_status()  # mid-restart: don't park on it
        try:
            st = ray_tpu.get(controller.status.remote(),
                             timeout=dl.remaining())
        except Exception:
            # The failed call doubles as the failure report that starts
            # the controller's restart. Retry once on the same handle
            # ONLY if the record is still ALIVE — that's the
            # fresh-handle-to-restarted-actor case (stale incarnation
            # hint, the failure taught the handle the live one); a
            # record now RESTARTING means a real outage: degrade.
            if not _controller_alive(controller):
                return _degraded_status()
            st = ray_tpu.get(controller.status.remote(),
                             timeout=dl.remaining())
    except Exception:
        return _degraded_status()
    if include_slo:
        try:
            from ray_tpu.core.runtime import get_core_worker
            from ray_tpu.serve.metrics import slo_summary

            agg = get_core_worker().controller.call("list_metrics",
                                                    timeout=10.0)
            slo = slo_summary(agg)
            for name, rec in st.items():
                rec["slo"] = slo.get(name, {})
        except Exception:
            # Histograms are additive detail: a briefly unreachable
            # head must not fail the whole status() call.
            from ray_tpu.util.ratelimit import log_every

            log_every("serve.status_slo", 30.0,
                      __import__("logging").getLogger(__name__),
                      "SLO summary fetch failed", exc_info=True)
    return st


def timelines(timeout: float = 30.0) -> Dict[str, Any]:
    """Engine step timelines per deployment/replica (see
    ``serve/steplog.py``); merged into a Chrome trace by
    ``python -m ray_tpu timeline --serve``."""
    controller = get_or_create_controller()
    return ray_tpu.get(controller.timelines.remote(), timeout=timeout)


def proxy_status(timeout: float = 30.0) -> Dict[str, Any]:
    """Per-node proxy health (node hex -> addr + consecutive failures)."""
    controller = get_or_create_controller()
    return ray_tpu.get(controller.proxy_status.remote(), timeout=timeout)


def delete(name: str, timeout: float = 30.0) -> None:
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete.remote(name), timeout=timeout)


def shutdown(drain_timeout_s: float = 10.0) -> None:
    """Tear down all deployments AND the controller actor. Proxies drain
    FIRST (stop accepting, let in-flight requests finish against
    still-live replicas — reference: proxy draining on serve shutdown)."""
    from ray_tpu.serve.controller import CONTROLLER_NAME

    controller = None
    try:
        # Lookup, not get_or_create: tearing down serve that was never
        # started must not SPAWN a control plane.
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        controller = None
    if controller is None:
        _Router.reset_all()
        return
    try:
        ray_tpu.get(controller.shutdown.remote(drain_timeout_s),
                    timeout=drain_timeout_s + 60.0)
    except Exception:  # graftlint: disable=swallowed-exception (best-effort serve teardown)
        pass
    finally:
        # Kill even when the graceful path timed out: a surviving named
        # controller whose _stop is set would be resolved by the next
        # serve.run as a zombie that never reconciles anything.
        if controller is not None:
            try:
                ray_tpu.kill(controller)
            except Exception:  # graftlint: disable=swallowed-exception (best-effort serve teardown)
                pass
        # Drop the durable checkpoint too: shutdown is the ONE
        # controller death that must not be survived — a controller
        # created later (next serve.run) starts fresh instead of
        # adopting the ghosts of the plane we just tore down. (The
        # graceful path already checkpointed empty state; this covers
        # the timed-out/killed path.)
        try:
            from ray_tpu.core.rpc_stubs import ControllerStub
            from ray_tpu.core.runtime import get_core_worker
            from ray_tpu.serve.controller import STATE_KEY

            ControllerStub(get_core_worker().controller).kv_del(STATE_KEY)
        except Exception:  # graftlint: disable=swallowed-exception (best-effort serve teardown)
            pass
    _Router.reset_all()


def start_http(host: str = "127.0.0.1", port: int = 0,
               ready_timeout_s: float = 60.0) -> Tuple[str, int]:
    """Enable per-node HTTP ingress (idempotent) and wait until every
    alive node has a listening proxy. Returns ONE reachable (host, port)
    — the proxy on this process's node when there is one, else the first
    (back-compat with the single-address shape; ``http_addresses()`` is
    the full per-node map). The wait polls CLIENT-side — the controller
    actor runs calls serially, so it must never block in enable_http."""
    controller = get_or_create_controller()
    state = ray_tpu.get(controller.enable_http.remote(host, port),
                        timeout=60.0)
    deadline = time.monotonic() + ready_timeout_s
    while not (state["addrs"] and state["want"]
               and len(state["addrs"]) >= state["want"]):
        if time.monotonic() > deadline:
            if state["addrs"]:
                break  # partial ingress beats none after the deadline
            raise RuntimeError(f"no serve proxies came up: {state}")
        time.sleep(0.2)
        state = ray_tpu.get(controller.http_ready.remote(), timeout=30.0)
    addrs = state["addrs"]
    try:
        from ray_tpu.core.runtime import get_core_worker

        local = get_core_worker().node_id.hex()
    except Exception:
        local = None
    addr = addrs.get(local) or next(iter(addrs.values()))
    return tuple(addr)


def http_addresses() -> Dict[str, tuple]:
    """Pure getter: node hex -> (host, port) of live proxies. Does NOT
    enable ingress (``start_http`` does) — a getter that re-enabled HTTP
    would silently undo ``stop_http``."""
    controller = get_or_create_controller()
    return ray_tpu.get(controller.http_addresses.remote(), timeout=30.0)


def stop_http(drain_timeout_s: float = 10.0) -> None:
    """Drain and stop every proxy (ingress off; deployments stay up).
    No-op when no controller exists — defensive cleanup must not SPAWN a
    control plane just to tell it to stop."""
    from ray_tpu.serve.controller import CONTROLLER_NAME

    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    ray_tpu.get(controller.disable_http.remote(drain_timeout_s),
                timeout=drain_timeout_s + 60.0)
