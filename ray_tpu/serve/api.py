"""serve.run / HTTP proxy / lifecycle.

Analogue of the reference's ``serve.run`` + proxy (``serve/api.py``,
``serve/_private/proxy.py:761,1130``). The HTTP proxy is a stdlib threading
HTTP server routing ``POST /<deployment>`` with a JSON body to the
deployment handle — the uvicorn/gRPC surface of the reference condensed to
the protocol that matters for parity tests; replicas and routing are the
real stack underneath.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from ray_tpu.serve.deployment import (
    Deployment,
    DeploymentHandle,
    _DeploymentState,
)

_deployments: Dict[str, _DeploymentState] = {}
_reconciler: Optional[threading.Thread] = None
_http_server: Optional[ThreadingHTTPServer] = None
_stop = threading.Event()


def run(app: Deployment, name: Optional[str] = None,
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    """Deploy (or redeploy) an application; returns its handle."""
    global _reconciler
    name = name or app.name
    if name in _deployments:
        _deployments[name].shutdown()
    state = _DeploymentState(app)
    _deployments[name] = state
    if _reconciler is None or not _reconciler.is_alive():
        _stop.clear()
        _reconciler = threading.Thread(target=_reconcile_loop,
                                       name="serve-reconcile", daemon=True)
        _reconciler.start()
    return DeploymentHandle(state)


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(_deployments[name])


def status() -> Dict[str, Any]:
    return {name: {"replicas": s.num_replicas()}
            for name, s in _deployments.items()}


def delete(name: str) -> None:
    state = _deployments.pop(name, None)
    if state is not None:
        state.shutdown()


def shutdown() -> None:
    global _http_server
    _stop.set()
    for name in list(_deployments):
        delete(name)
    if _http_server is not None:
        _http_server.shutdown()
        _http_server = None


def _reconcile_loop() -> None:
    """Controller reconcile: autoscaling + dead-replica replacement
    (reference: ServeController loop)."""
    while not _stop.wait(0.25):
        for state in list(_deployments.values()):
            try:
                state.reconcile()
            except Exception:
                pass


class _ProxyHandler(BaseHTTPRequestHandler):
    def do_POST(self):  # noqa: N802 (stdlib API)
        name = self.path.strip("/").split("/")[0]
        state = _deployments.get(name)
        if state is None:
            self.send_error(404, f"no deployment {name!r}")
            return
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b"null"
        try:
            payload = json.loads(body)
            result = state.submit("__call__", (payload,), {}).result(
                timeout=60)
            data = json.dumps(result).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        except Exception as e:  # noqa: BLE001
            self.send_error(500, str(e))

    def log_message(self, *args):  # silence
        pass


def start_http(host: str = "127.0.0.1", port: int = 0) -> tuple:
    """Start the HTTP proxy; returns (host, port)."""
    global _http_server
    _http_server = ThreadingHTTPServer((host, port), _ProxyHandler)
    threading.Thread(target=_http_server.serve_forever, name="serve-http",
                     daemon=True).start()
    return _http_server.server_address
