"""Serve data plane: per-node HTTP proxy actors.

Analogue of the reference's managed ``ProxyActor``
(``serve/_private/proxy.py:131,540,761,1130``) and its lifecycle manager
(``proxy_state.py``): the serve controller runs one ProxyActor on every
alive node (node-affinity scheduled), health-checks it, replaces it when
it dies, and drains it before removing a node's ingress. The HTTP server
lives INSIDE the actor's worker process — not in whichever driver called
``serve.run`` — so ingress survives driver exit and scales with the
cluster, and request routing (DeploymentHandle -> router -> replica) runs
in the proxy process too.

Request path: HTTP -> longest-prefix route table (cached from the serve
controller) -> DeploymentHandle -> pow-2 router -> replica. Streaming
responses use chunked transfer with one JSON line per yielded item.
"""

from __future__ import annotations

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

_STREAM_END = object()


class _ClientDisconnected(Exception):
    """The HTTP client went away mid-response; nothing can be written."""


def _lifecycle_error(e: BaseException):
    """Walk an exception chain (TaskError.cause / RemoteCallError.cause /
    __cause__) for a typed request-lifecycle error so the proxy can map
    it onto the right status code instead of a blanket 500."""
    from ray_tpu.core.errors import (DeadlineExceededError, OverloadedError,
                                     RequestCancelledError)

    seen = set()
    cur: Optional[BaseException] = e
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if isinstance(cur, (OverloadedError, DeadlineExceededError,
                            RequestCancelledError)):
            return cur
        nxt = getattr(cur, "cause", None)
        cur = nxt if isinstance(nxt, BaseException) else cur.__cause__
    return None


class _InFlight:
    """Proxy request accounting for graceful draining."""

    def __init__(self):
        self.count = 0
        self.cond = threading.Condition()

    def __enter__(self):
        with self.cond:
            self.count += 1
        return self

    def __exit__(self, *exc):
        with self.cond:
            self.count -= 1
            self.cond.notify_all()

    def drain(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self.cond:
            while self.count > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self.cond.wait(min(remaining, 1.0))
        return True


class _RouteTable:
    """Longest-prefix route lookup against the serve controller's route
    table, cached briefly (the reference's proxy gets pushed route updates
    via LongPollHost; a 2 s pull cache gives the same convergence window
    without a standing subscription per proxy).

    Outage-tolerant by construction: the controller is LOOKED UP, never
    created (a proxy must not spawn a control plane to route a request),
    a failed refresh serves the stale cache and backs off further
    refresh attempts for 2 s — so during a controller outage the data
    plane keeps routing on its last known table, paying at most one
    short probe per backoff window instead of one per request. With no
    cache at all, ``resolve`` returns None and the caller falls back to
    the first path segment — fresh proxies still route the common
    ``/<app>`` shape with the controller down."""

    def __init__(self):
        self._cache: Optional[Tuple[float, Dict[str, str]]] = None
        self._backoff_until = 0.0
        self._lock = threading.Lock()

    def invalidate(self) -> None:
        with self._lock:
            self._cache = None

    def _refresh(self, now: float) -> Optional[Dict[str, str]]:
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        from ray_tpu.serve.api import _controller_alive

        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            if not _controller_alive(controller):
                # Mid-restart: degrade WITHOUT parking a blocking call
                # on the request path — stale routes serve meanwhile.
                raise RuntimeError("serve controller not ALIVE")
            try:
                routes = ray_tpu.get(controller.get_routes.remote(),
                                     timeout=5.0)
            except Exception:
                # Same-handle retry, but only against a live record: a
                # restarted controller rejects a fresh handle's first
                # call (stale incarnation hint); a record that just
                # went RESTARTING is an outage — the failed call above
                # already reported it.
                if not _controller_alive(controller):
                    raise
                routes = ray_tpu.get(controller.get_routes.remote(),
                                     timeout=5.0)
        except Exception:
            # Dead/restarting controller. The failed actor call above
            # doubles as the failure report that triggers its restart;
            # meanwhile the stale cache keeps the data plane moving.
            with self._lock:
                self._backoff_until = time.monotonic() + 2.0
            return None
        with self._lock:
            self._cache = (now, routes)
            self._backoff_until = 0.0
        return routes

    def resolve(self, path: str) -> Optional[str]:
        now = time.monotonic()
        with self._lock:
            cache = self._cache
            backoff_until = self._backoff_until
        routes = None
        if (cache is None or now - cache[0] > 2.0) \
                and now >= backoff_until:
            routes = self._refresh(now)
        if routes is None:
            routes = {} if cache is None else cache[1]
        path = "/" + path.strip("/")
        best = None
        for prefix, name in routes.items():
            if (prefix == "/" or path == prefix
                    or path.startswith(prefix + "/")):
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, name)
        return best[1] if best else None


def make_handler(in_flight: _InFlight, routes: _RouteTable):
    """Build the request-handler class bound to one proxy's state."""
    from ray_tpu.serve.deployment import DeploymentHandle

    class _ProxyHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"  # chunked transfer needs 1.1

        def send_response(self, code, message=None):  # noqa: A003
            self._status = code  # observed by the request metrics below
            super().send_response(code, message)

        def do_POST(self):  # noqa: N802 (stdlib API)
            from contextlib import nullcontext

            from ray_tpu.core.config import config as rt_config
            from ray_tpu.util import tracing

            t0 = time.perf_counter()
            self._status = 0
            self._dep_name = ""
            # Inbound propagation: a client that opened its own span
            # ships it as X-Trace-Id/X-Parent-Span headers and this
            # request's whole tree parents under it — the client
            # process becomes the root of the cross-process trace.
            hdr_t = self.headers.get("X-Trace-Id", "")
            hdr_p = self.headers.get("X-Parent-Span", "")
            inbound = (hdr_t, hdr_p) if hdr_t and hdr_p else None
            with in_flight:
                # The request's ROOT span (or the child of the client's
                # span): everything below it — router span, attempt
                # spans, replica execution, engine queue-wait/prefill/
                # decode — parents back here, so one HTTP request
                # renders as one causally-linked tree across processes
                # in `ray_tpu timeline --serve`.
                if rt_config.serve_trace_spans:
                    with tracing.resume(inbound), \
                            tracing.trace(f"http:{self.path}",
                                          method="POST"):
                        self._handle()
                else:
                    self._handle()
            if rt_config.serve_metrics_enabled:
                from ray_tpu.serve import metrics as smetrics

                tags = {"deployment": self._dep_name or "-"}
                smetrics.HTTP_LATENCY.observe(
                    time.perf_counter() - t0, tags)
                smetrics.HTTP_REQUESTS.inc(
                    1.0, {**tags, "code": str(self._status or 0)})

        def do_GET(self):  # noqa: N802
            # Health endpoint (reference: proxy.py /-/healthz).
            if self.path.rstrip("/") in ("/-/healthz", "/healthz"):
                data = b"ok"
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path.rstrip("/") in ("/metrics", "/-/metrics"):
                self._serve_metrics()
            else:
                self.send_error(404)

        def _serve_metrics(self) -> None:
            """Prometheus exposition text from the cluster controller's
            aggregated registry (reference: the node agent's exporter).
            Serving it from the INGRESS port means a Prometheus scraping
            the proxies sees every deployment's TTFT / inter-token /
            queue-wait histograms — and on disaggregated fleets the
            ``serve_handoff_*`` descriptor-size / lease-latency /
            lease-event series — without reaching the control plane."""
            from ray_tpu.core.runtime import get_core_worker

            try:
                text = get_core_worker().controller.call(
                    "metrics_text", timeout=10.0)
            except Exception as e:  # noqa: BLE001 — head unreachable
                self._send_plain(503, f"metrics unavailable: {e}")
                return
            data = text.encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _request_timeout_s(self) -> Optional[float]:
            """The request's end-to-end budget: client header
            ``X-Request-Timeout-S`` wins; else the
            ``serve_request_timeout_s`` config default (0 = none)."""
            from ray_tpu.core.config import config as rt_config

            raw = self.headers.get("X-Request-Timeout-S", "")
            if raw:
                try:
                    val = float(raw)
                    if val > 0:
                        return val
                except ValueError:
                    pass  # malformed header: fall through to the default
            default = rt_config.serve_request_timeout_s
            return default if default > 0 else None

        def _send_plain(self, code: int, message: str,
                        headers: Tuple[Tuple[str, str], ...] = ()) -> None:
            data = (message + "\n").encode()
            self.send_response(code)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(data)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _send_lifecycle_error(self, e: BaseException) -> bool:
            """Typed lifecycle outcomes get real status codes: shed ->
            503 + Retry-After (from the replica's throughput estimate),
            deadline -> 504, client-cancelled -> 499. Returns False when
            ``e`` is not a lifecycle error."""
            from ray_tpu.core.errors import (DeadlineExceededError,
                                             OverloadedError,
                                             RequestCancelledError)

            cause = _lifecycle_error(e)
            if isinstance(cause, OverloadedError):
                retry = max(1, math.ceil(cause.retry_after_s))
                self._send_plain(503, f"overloaded: {cause}",
                                 (("Retry-After", str(retry)),))
            elif isinstance(cause, DeadlineExceededError):
                self._send_plain(504, f"deadline exceeded: {cause}")
            elif isinstance(cause, RequestCancelledError):
                self._send_plain(499, f"request cancelled: {cause}")
            else:
                return False
            return True

        def _handle(self) -> None:
            from concurrent.futures import TimeoutError as FutTimeout

            parts = self.path.strip("/").split("/")
            # Route table first (supports custom route_prefix); fall back
            # to the first path segment as the app name.
            name = routes.resolve(self.path) or parts[0]
            self._dep_name = name  # request-metric deployment label
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b"null"
            model_id = self.headers.get("serve_multiplexed_model_id", "")
            streaming = (self.headers.get("x-serve-stream", "")
                         or self.headers.get("X-Serve-Stream", ""))
            timeout_s = self._request_timeout_s()
            try:
                payload = json.loads(body)
                handle = DeploymentHandle(name,
                                          multiplexed_model_id=model_id,
                                          timeout_s=timeout_s)
                if streaming:
                    self._stream_response(handle, payload, name)
                    return
                # The deadline rides with the request (router retries
                # stop at it; the engine frees the slot at it). The
                # local wait gets a grace window past it so the TYPED
                # DeadlineExceededError from the replica wins the race
                # against this blunt local timeout.
                result = handle.remote(payload).result(
                    timeout=(timeout_s + 10.0) if timeout_s else None)
                data = json.dumps(result).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            except _ClientDisconnected:
                self.close_connection = True  # socket is gone; cancel done
            except KeyError:
                self.send_error(404, f"no deployment {name!r}")
            except FutTimeout:
                self._send_plain(504, "deadline exceeded: no reply from "
                                      "the deployment in time")
            except Exception as e:  # noqa: BLE001
                if not self._send_lifecycle_error(e):
                    self.send_error(500, str(e))

        def _stream_response(self, handle, payload, name: str) -> None:
            """Chunked transfer encoding, one JSON line per yielded item
            (reference: proxy.py streaming/chunked responses). The
            generator is pulled incrementally — chunks reach the client as
            the replica produces them.

            Errors BEFORE the first item become real HTTP errors (the
            generator is primed before any header ships); a mid-stream
            error can't rewrite the status line, so it becomes an error
            record in the stream and the connection closes (never a second
            response on a keep-alive socket)."""
            stream = handle.stream(payload)
            try:
                first = next(stream, _STREAM_END)
            except KeyError:
                self.send_error(404, f"no deployment {name!r}")
                return
            except Exception as e:  # noqa: BLE001
                if not self._send_lifecycle_error(e):
                    self.send_error(500, str(e))
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/jsonlines")
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header("Connection", "close")
            self.end_headers()

            def chunk(data: bytes) -> None:
                self.wfile.write(f"{len(data):x}\r\n".encode())
                self.wfile.write(data + b"\r\n")

            try:
                if first is not _STREAM_END:
                    chunk(json.dumps(first).encode() + b"\n")
                    for item in stream:
                        chunk(json.dumps(item).encode() + b"\n")
            except (BrokenPipeError, ConnectionError) as e:
                # Client hung up mid-stream: nothing can be written, but
                # the disconnect must PROPAGATE — the finally's
                # stream.close() cancels the replica stream, which
                # cancels the engine request and frees its slot.
                raise _ClientDisconnected(str(e)) from e
            except Exception as e:  # noqa: BLE001 — headers already sent
                # Mid-stream failures (incl. DeadlineExceeded) can't
                # rewrite the status line; they become an error record in
                # the stream and the connection closes.
                chunk(json.dumps(
                    {"__serve_stream_error__": str(e)}).encode() + b"\n")
            finally:
                # Deterministic cancellation: closing the generator runs
                # the router's finally (cancel_stream -> replica -> engine
                # .cancel) NOW, not at some later GC.
                stream.close()
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass
                self.close_connection = True

        def log_message(self, *args):  # silence
            pass

    return _ProxyHandler


class ProxyActor:
    """One per node, supervised by the serve controller. The HTTP server
    runs on threads inside this actor's worker process; the actor's RPC
    surface is control-only (health, drain, address)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._in_flight = _InFlight()
        self._routes = _RouteTable()
        self._draining = False
        self._server = ThreadingHTTPServer(
            (host, port), make_handler(self._in_flight, self._routes))
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="serve-proxy-http",
            daemon=True)
        self._thread.start()

    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def node_hex(self) -> str:
        from ray_tpu.core.runtime import get_core_worker

        return get_core_worker().node_id.hex()

    def healthz(self) -> Dict[str, Any]:
        return {"ok": not self._draining,
                "in_flight": self._in_flight.count,
                "addr": self._server.server_address}

    def invalidate_routes(self) -> None:
        self._routes.invalidate()

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Stop accepting, wait for in-flight requests (reference: proxy
        draining before node removal / serve shutdown)."""
        self._draining = True
        self._server.shutdown()  # accept loop stops; handlers continue
        ok = self._in_flight.drain(timeout_s)
        self._server.server_close()
        return ok
