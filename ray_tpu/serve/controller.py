"""ServeController: the serving control plane, as a named actor.

Analogue of the reference's ``ServeController`` actor
(``serve/_private/controller.py:86``; ``deploy_application`` :719,
``deployment_state.py`` reconciliation): it owns deployment configs and
replica actors, heals dead replicas, autoscales on replica-reported load
(``autoscaling_policy.py:12``), and pushes routing snapshots to every
handle via the cluster pubsub hub (the reference's ``LongPollHost``,
``long_poll.py:173``). Because it is an actor — not driver state — the
serving plane survives the deploying driver's exit; any process can pick
up a ``DeploymentHandle`` by name.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core.config import config
from ray_tpu.core.rpc_stubs import ControllerStub
from ray_tpu.util import faultinject
from ray_tpu.util.ratelimit import log_every

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "_ray_tpu_serve_controller"
SNAPSHOT_CHANNEL = "serve_routes"
# Control-plane FT (mirrors core/controller.py save_state/_restore_state,
# through the core KV instead of a file): every mutating op checkpoints
# under STATE_KEY, fenced by the EPOCH_NAME epoch lease — a restarted
# controller bumps the epoch, restores the checkpoint, and ADOPTS the
# replicas that survived; a deposed zombie's writes are rejected.
STATE_KEY = "serve:controller:state"
EPOCH_NAME = "serve_controller"


def autoscale_load(stats: Dict[str, Any]) -> float:
    """One replica's autoscaler load signal from its reported stats.

    Base signal: ``max(ongoing, load)`` — HTTP concurrency vs the
    engine's own backlog (slots + queue + prefill backlog), whichever
    is worse.

    Speculative replicas would OVER-report headroom from that alone: a
    spec engine's slots complete requests ``(1 + accept_rate * k)``
    tokens per step instead of 1, so the same backlog clears faster at
    high acceptance — but at LOW acceptance each slot still pays the
    (k+1)-token verify forward per emitted token, and a draft pool
    under pressure keeps new seats draftless (no speedup at full spec
    cost). Scale the signal by the spec slowdown factor
    ``(k + 1) / (1 + accept_rate * k)`` (1.0 at perfect acceptance =
    the engine really does have spec-sized headroom; (k+1) at zero
    acceptance = every slot is doing verify work for nothing), plus a
    draft-pool-pressure bump when the pool is nearly exhausted."""
    load = float(max(stats.get("ongoing", 0) or 0,
                     stats.get("load", 0) or 0))
    spec = stats.get("spec")
    if not isinstance(spec, dict):
        return load
    k = float(spec.get("k", 0) or 0)
    if k <= 0:
        return load
    accept = spec.get("accept_rate")
    accept = 0.0 if accept is None else min(1.0, max(0.0, float(accept)))
    load *= (k + 1.0) / (1.0 + accept * k)
    total = float(spec.get("draft_pages_total", 0) or 0)
    if total > 0:
        occupancy = 1.0 - float(spec.get("draft_pages_free", 0)) / total
        if occupancy > 0.75:
            # Draft pool nearly dry: new admissions seat draftless and
            # decode at 1 token/step while paying spec overheads.
            load *= 1.0 + (occupancy - 0.75)
    return load


class ReplicaRecord:
    def __init__(self, handle, replica_id: str,
                 sub_slice: Optional[Dict[str, Any]] = None):
        self.handle = handle
        self.replica_id = replica_id
        self.last_stats: Dict[str, Any] = {}
        # Sub-slice assignment a mesh-parallel replica spans (controller
        # ``reserve_subslice`` result): released when the replica dies.
        self.sub_slice = sub_slice
        self.created = time.monotonic()


class DeploymentRecord:
    def __init__(self, name: str, cls_blob: bytes, init_args, init_kwargs,
                 cfg: Dict[str, Any]):
        self.name = name
        self.cls_blob = cls_blob
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.cfg = cfg
        self.replicas: List[ReplicaRecord] = []
        self.next_replica_ord = 0
        self.last_scale = time.monotonic()
        self.deleting = False
        self.pub_version = 0      # last version _publish saw on the hub
        self.last_pub_check = 0.0  # hub-version heal throttle
        # Serializes structural changes (deploy's settle vs reconcile) so
        # two threads can't both observe len < target and double-add.
        self.lock = threading.Lock()


class ProxyRecord:
    def __init__(self, node_hex: str, handle):
        self.node_hex = node_hex
        self.handle = handle
        self.addr: Optional[tuple] = None
        self.failures = 0  # consecutive health-check failures


class ServeController:
    """Runs as a named actor; all methods are invoked via actor calls."""

    def __init__(self):
        faultinject.check("serve.controller.init")
        self._deployments: Dict[str, DeploymentRecord] = {}
        self._last_models: Dict[str, Any] = {}
        self._routes: Dict[str, str] = {}  # HTTP route prefix -> app name
        # HTTP data plane (reference: proxy_state.py): desired config +
        # one ProxyActor per alive node, reconciled below.
        self._http_cfg: Optional[Dict[str, Any]] = None
        self._proxies: Dict[str, ProxyRecord] = {}  # node hex -> record
        # Sub-slice reservation ids whose release RPC failed (head
        # briefly unreachable): retried every reconcile tick — a
        # silently dropped release would strand the chips until the
        # hosting node dies. Guarded by _lock; PERSISTED in the
        # checkpoint (a controller death with a queued release must not
        # leak the chips until node death).
        self._pending_releases: List[str] = []
        self._lock = threading.Lock()
        # Serializes checkpoint writers (a slow save interleaving with a
        # fresh one would let the stale snapshot win the KV write).
        self._save_mutex = threading.Lock()
        self._stop = threading.Event()
        # Epoch lease (reference: GCS leader fencing): bumped on every
        # controller (re)start, stamped into every snapshot, replica
        # assignment, and fenced KV write. 0 = not yet acquired (head
        # unreachable at start; the reconcile loop keeps trying).
        self._epoch = 0
        self._fenced = False
        self._acquire_epoch()
        # Rebuild from the last checkpoint BEFORE the reconcile threads
        # start: adoption must finish deciding which replicas live so
        # the first reconcile tick heals instead of double-spawning.
        self._restore_state()
        from ray_tpu.util import metrics as um

        um.add_collector(self._collect_metrics)
        self._reconciler = threading.Thread(
            target=self._reconcile_loop, name="serve-reconcile", daemon=True)
        self._reconciler.start()
        # Proxies reconcile on their OWN thread: serial 5 s health probes
        # of a hung proxy must not delay replica healing/autoscaling.
        self._proxy_reconciler = threading.Thread(
            target=self._proxy_loop, name="serve-proxy-reconcile",
            daemon=True)
        self._proxy_reconciler.start()
        # Record the adoption outcome under the new epoch immediately:
        # dying again before the first mutation must not replay the
        # previous incarnation's view of the world.
        self._save_state()

    # ------------------------------------------------- durable state (FT)

    def _acquire_epoch(self) -> None:
        from ray_tpu.core.runtime import get_core_worker

        try:
            self._epoch = ControllerStub(
                get_core_worker().controller).epoch_bump(
                    EPOCH_NAME, timeout=config.ctrl_call_timeout_s)
        except Exception:
            # Head unreachable at start: run epoch-less for now —
            # publishes go out unfenced and checkpoints are skipped —
            # and the reconcile loop keeps retrying the lease.
            log_every("serve.epoch", 10.0, logger,
                      "serve controller epoch lease unavailable; "
                      "running unfenced until the head answers",
                      exc_info=True)

    def _snapshot_state(self) -> Dict[str, Any]:
        """Copy every durable field. rec.replicas is read WITHOUT
        rec.lock on purpose: _save_state runs on paths that already
        hold rec.lock (_add_replica's spawn-failure release under
        _settle), so taking it here would be a lock-order cycle with
        _save_mutex (graftlint caught exactly that). The GIL makes the
        ``list(...)`` copy coherent; a snapshot racing a replica
        append/remove just records the neighboring state, and the
        mutating path's own save (deploy/reconcile both end with one)
        supersedes it within the same tick — same discipline as
        ``status()``'s lock-free replica reads."""
        with self._lock:
            recs = list(self._deployments.values())
            state: Dict[str, Any] = {
                "epoch": self._epoch,
                "routes": dict(self._routes),
                "http_cfg": (dict(self._http_cfg)
                             if self._http_cfg else None),
                "proxies": {
                    n: {"actor_id": p.handle.actor_id.binary(),
                        "addr": tuple(p.addr) if p.addr else None}
                    for n, p in self._proxies.items()},
                "pending_releases": list(self._pending_releases),
            }
        deployments = []
        for rec in recs:
            replicas = [
                {"replica_id": r.replica_id,
                 "actor_id": r.handle.actor_id.binary(),
                 "sub_slice": (dict(r.sub_slice)
                               if r.sub_slice else None)}
                for r in list(rec.replicas)]
            deployments.append({
                "name": rec.name, "cls_blob": rec.cls_blob,
                "init_args": rec.init_args,
                "init_kwargs": rec.init_kwargs, "cfg": rec.cfg,
                "next_replica_ord": rec.next_replica_ord,
                "pub_version": rec.pub_version,
                "deleting": rec.deleting, "replicas": replicas})
        state["deployments"] = deployments
        return state

    def _save_state(self) -> None:
        """Checkpoint the control plane through the core KV, fenced by
        the epoch lease. Every state-mutating handler must reach this
        before returning (graftlint: checkpoint-missing-save); the
        reconcile/proxy loops save when their pass changed anything. A
        False from the fenced write means a newer epoch exists — this
        instance is a zombie and ceases all mutation."""
        faultinject.check("serve.controller.save_state")
        if self._fenced or self._epoch <= 0:
            return
        import pickle

        from ray_tpu.core.runtime import get_core_worker

        with self._save_mutex:
            blob = pickle.dumps(self._snapshot_state())
            try:
                # _save_mutex exists precisely to serialize this RPC
                # with concurrent snapshots: an unserialized slow save
                # would let a STALE snapshot overwrite a fresher one.
                # Nothing else ever takes _save_mutex.
                ok = ControllerStub(
                    get_core_worker().controller).kv_put_fenced(
                        STATE_KEY, blob, self._epoch, EPOCH_NAME,
                        timeout=config.ctrl_call_timeout_s)
            except Exception:
                # Head blip: state is stale until the next mutation or
                # reconcile-tick change saves again. Never silent —
                # degraded fault tolerance is an operator concern.
                log_every("serve.save_state", 10.0, logger,
                          "serve controller checkpoint failed; restart "
                          "would replay the previous checkpoint",
                          exc_info=True)
                return
        if not ok:
            self._fence("the checkpoint KV rejected this epoch's write")

    def _fence(self, why: str) -> None:
        """A newer controller epoch exists: this instance is a zombie
        (its replacement already restored and owns the plane). Cease
        every mutation — but do NOT drain: the replicas now belong to
        the successor, and killing them from here would be exactly the
        split-brain damage fencing exists to prevent."""
        if self._fenced:
            return
        self._fenced = True
        self._stop.set()
        logger.warning(
            "serve controller epoch %s fenced (%s): ceasing mutation; "
            "the successor controller owns the serve plane", self._epoch,
            why)

    def _restore_state(self) -> None:
        """Rebuild from the last checkpoint and ADOPT surviving actors.

        Replicas are pinged (concurrently, one shared deadline): the
        live ones keep their actor AND their sub-slice reservation —
        the topology view outlived the controller, so re-reserving
        would double-book chips, and respawning would re-pay prefill
        and weight loading for no reason. Only the dead are replaced
        (their reservations queue for release), mid-delete deployments
        finish draining, and every snapshot republishes under the new
        epoch with its persisted version floor so router clocks stay
        monotonic."""
        faultinject.check("serve.controller.restore")
        import pickle

        from ray_tpu.core.runtime import get_core_worker

        try:
            blob = ControllerStub(
                get_core_worker().controller).kv_get(
                    STATE_KEY, timeout=config.ctrl_call_timeout_s)
        except Exception:
            log_every("serve.restore", 10.0, logger,
                      "serve controller checkpoint unreadable (head "
                      "unreachable); starting empty", exc_info=True)
            return
        if not blob:
            return
        try:
            state = pickle.loads(blob)
        except Exception:
            # A corrupt checkpoint must not brick the replacement
            # controller: starting empty (deployments re-run) beats not
            # starting (routing stalls forever).
            logger.warning("serve controller checkpoint corrupt; "
                           "starting empty", exc_info=True)
            return
        from ray_tpu.core.actor import ActorHandle
        from ray_tpu.core.ids import ActorID

        self._routes = dict(state.get("routes") or {})
        self._http_cfg = state.get("http_cfg")
        self._pending_releases = list(state.get("pending_releases") or [])
        for node_hex, p in (state.get("proxies") or {}).items():
            proxy = ProxyRecord(node_hex,
                                ActorHandle(ActorID(p["actor_id"])))
            proxy.addr = tuple(p["addr"]) if p.get("addr") else None
            # Adopted as-is: the proxy loop health-checks at 1 Hz and
            # replaces the dead, exactly as for any hung proxy.
            self._proxies[node_hex] = proxy
        pings = []
        for d in state.get("deployments") or []:
            rec = DeploymentRecord(d["name"], d["cls_blob"],
                                   d["init_args"], d["init_kwargs"],
                                   d["cfg"])
            rec.next_replica_ord = d["next_replica_ord"]
            rec.pub_version = d["pub_version"]
            rec.deleting = bool(d.get("deleting"))
            self._deployments[d["name"]] = rec
            for r in d.get("replicas") or []:
                handle = ActorHandle(ActorID(r["actor_id"]))
                # Fire all pings first; gather below on one deadline.
                pings.append((rec, r, handle, handle.ping.remote()))
        adopted = dead = 0
        deadline = time.monotonic() + config.serve_adopt_timeout_s
        for rec, r, handle, ref in pings:
            try:
                ray_tpu.get(ref, timeout=max(0.2,
                                             deadline - time.monotonic()))
            except Exception:
                dead += 1
                sub = r.get("sub_slice")
                if sub:
                    # The dead replica's reservation releases through
                    # the normal retry queue (idempotent on the head).
                    self._pending_releases.append(sub["reservation_id"])
                continue
            rec.replicas.append(
                ReplicaRecord(handle, r["replica_id"], r.get("sub_slice")))
            adopted += 1
            try:
                # Adoption handshake: the replica now reports THIS
                # epoch as its owner (doctor's orphan-replica gauge).
                handle.set_owner_epoch.remote(self._epoch)
            except Exception:
                log_every("serve.adopt_epoch", 10.0, logger,
                          "epoch push to adopted replica %s failed",
                          r["replica_id"], exc_info=True)
        # Deployments the old controller died mid-delete: finish them.
        for rec in [r for r in self._deployments.values() if r.deleting]:
            self._drain(rec)
            del self._deployments[rec.name]
            self._publish(rec)
        # Routing resumes here: epoch-stamped snapshots above the
        # persisted version floor (MTTR clock stops on this publish).
        for rec in self._deployments.values():
            self._publish(rec)
        if pings or self._pending_releases:
            logger.info(
                "serve controller epoch %s restored: %d replica(s) "
                "adopted in place, %d dead queued for replacement, %d "
                "pending sub-slice release(s) resumed", self._epoch,
                adopted, dead, len(self._pending_releases))

    # ------------------------------------------------------------ deploy

    def deploy(self, name: str, cls_blob: bytes, init_args, init_kwargs,
               cfg: Dict[str, Any]) -> Optional[int]:
        """Create or update a deployment (reference: deploy_application).
        Config change redeploys replicas; scale-only change adjusts count.

        The old record is marked ``deleting`` under the lock BEFORE its
        replicas drain, and the reconcile loop re-validates record identity
        under the same lock — otherwise a reconcile tick that snapshotted
        the old record could resurrect old-class replicas and publish them
        over the live name."""
        faultinject.check("serve.controller.deploy")
        with self._lock:
            old = self._deployments.get(name)
            rec = DeploymentRecord(name, cls_blob, init_args, init_kwargs,
                                   cfg)
            drain_old = False
            if old is not None:
                # The version floor survives record replacement: routers'
                # long-poll clocks are per deployment NAME, so a redeploy
                # publishing below the old record's version would strand
                # every existing handle.
                rec.pub_version = old.pub_version
                if (old.cls_blob == cls_blob
                        and old.init_args == init_args
                        and old.init_kwargs == init_kwargs):
                    rec.replicas = old.replicas  # rolling config update
                    rec.next_replica_ord = old.next_replica_ord
                else:
                    old.deleting = True
                    drain_old = True
            self._deployments[name] = rec
        if drain_old:
            self._drain(old)
        with rec.lock:
            doomed = self._settle(rec)
        # Kill downscaled replicas OUTSIDE rec.lock: ray_tpu.kill is a
        # controller RPC, and holding the record lock across it would
        # stall every reconcile tick on this deployment behind a dead
        # node's timeout (graftlint: lock-held-blocking).
        for replica in doomed:
            self._kill_replica(replica)
        version = self._publish(rec)
        self._save_state()
        return version

    # ------------------------------------------------- autopilot hooks

    def autopilot_resize(self, deployment: str, delta: int = 1,
                         epoch: int = 0) -> Dict[str, Any]:
        """Autopilot's resize-deployment action (SLO burn). Fenced on
        the serve-controller epoch the autopilot OBSERVED: a mismatch
        means this plane restarted (and re-settled) since the evidence
        was collected, so the action no-ops — the successor already
        reconciled against fresh reality. Autoscaling deployments get
        their floor raised (the autoscaler stays in charge of the rest);
        fixed deployments get num_replicas bumped. The reconcile loop
        settles toward the new target on its next tick."""
        if self._fenced or int(epoch) != self._epoch:
            return {"ok": False, "reason": "stale-epoch",
                    "epoch": self._epoch}
        with self._lock:
            rec = self._deployments.get(deployment)
        if rec is None or rec.deleting:
            return {"ok": False, "reason": "unknown-deployment"}
        return self._apply_resize(rec, delta)

    def _apply_resize(self, rec: "DeploymentRecord",
                      delta: int) -> Dict[str, Any]:
        """The mutating half (checkpoint-obliged: every exit saves)."""
        with rec.lock:
            auto = rec.cfg.get("autoscaling")
            if auto:
                auto["min_replicas"] = max(1, min(
                    int(auto.get("max_replicas", 1)),
                    int(auto.get("min_replicas", 1)) + int(delta)))
                target = auto["min_replicas"]
            else:
                rec.cfg["num_replicas"] = max(
                    1, int(rec.cfg.get("num_replicas", 1)) + int(delta))
                target = rec.cfg["num_replicas"]
        self._save_state()
        return {"ok": True, "target": target, "epoch": self._epoch}

    def autopilot_shed(self, deployment: str, queue_max: int,
                       epoch: int = 0) -> Dict[str, Any]:
        """Autopilot's shed-tenant action (sustained rpc-backpressure):
        tighten the deployment's admission cap so overload sheds at
        enqueue (OverloadedError -> HTTP 503 + Retry-After — PR 3's
        admission machinery) instead of queueing into minutes of
        latency and backpressuring the control plane. Fenced like
        autopilot_resize. The override persists in the deployment cfg
        (checkpointed; re-applied to respawned replicas) until a
        redeploy replaces the record."""
        if self._fenced or int(epoch) != self._epoch:
            return {"ok": False, "reason": "stale-epoch",
                    "epoch": self._epoch}
        with self._lock:
            rec = self._deployments.get(deployment)
        if rec is None or rec.deleting:
            return {"ok": False, "reason": "unknown-deployment"}
        return self._apply_shed(rec, queue_max)

    def _apply_shed(self, rec: "DeploymentRecord",
                    queue_max: int) -> Dict[str, Any]:
        """The mutating half (checkpoint-obliged: every exit saves)."""
        with rec.lock:
            rec.cfg["queue_max_override"] = max(1, int(queue_max))
            replicas = list(rec.replicas)
        applied = 0
        for r in replicas:
            try:
                r.handle.set_admission.remote(rec.cfg["queue_max_override"])
                applied += 1
            except Exception:
                log_every("serve.autopilot_shed", 10.0, logger,
                          "admission-cap push to replica %s failed",
                          r.replica_id, exc_info=True)
        self._save_state()
        return {"ok": True, "queue_max": rec.cfg["queue_max_override"],
                "replicas": applied, "epoch": self._epoch}

    def _target_replicas(self, rec: DeploymentRecord) -> int:
        auto = rec.cfg.get("autoscaling")
        if auto:
            return max(auto["min_replicas"],
                       min(auto["max_replicas"], len(rec.replicas) or
                           auto["min_replicas"]))
        return rec.cfg.get("num_replicas", 1)

    def _settle(self, rec: DeploymentRecord) -> List[ReplicaRecord]:
        """Converge the replica count toward target under rec.lock.
        Returns the replicas a downscale removed — the caller kills them
        after releasing the lock. A replica that cannot be PLACED (no
        ICI-contiguous sub-slice free for its mesh) stops the upscale:
        the deployment stays below target and the reconcile loop retries
        when topology frees up — it is never placed on a fragment."""
        target = self._target_replicas(rec)
        doomed: List[ReplicaRecord] = []
        while len(rec.replicas) < target:
            if not self._add_replica(rec):
                break
        while len(rec.replicas) > target:
            doomed.append(self._remove_replica(rec))
        return doomed

    @staticmethod
    def _mesh_shape(rec: DeploymentRecord) -> Optional[tuple]:
        ms = rec.cfg.get("mesh_shape")
        return tuple(int(x) for x in ms) if ms else None

    @staticmethod
    def _mesh_chips(rec: DeploymentRecord) -> int:
        ms = ServeController._mesh_shape(rec)
        return ms[0] * ms[1] if ms else 1

    def _add_replica(self, rec: DeploymentRecord) -> bool:
        from ray_tpu.serve.replica import ReplicaActor

        replica_id = f"{rec.name}#{rec.next_replica_ord}"
        mesh_shape = self._mesh_shape(rec)
        sub = None
        if mesh_shape is not None:
            # Mesh-parallel replica: reserve an ICI-contiguous sub-slice
            # BEFORE spawning. A refusal (None) means no single slice
            # can host the mesh — the replica queues (reconcile retries)
            # rather than spawning on a fragment straddling slices.
            from ray_tpu.core.runtime import get_core_worker

            chips = mesh_shape[0] * mesh_shape[1]
            try:
                sub = ControllerStub(
                    get_core_worker().controller).reserve_subslice(
                        replica_id, chips, list(mesh_shape),
                        timeout=config.ctrl_call_timeout_s)
            except Exception:
                sub = None  # head unreachable counts as no capacity
            if sub is None:
                log_every(f"serve.subslice.{rec.name}", 5.0, logger,
                          "no contiguous %sx%s sub-slice for replica %s "
                          "of %r; deployment stays below target until "
                          "topology frees", mesh_shape[0], mesh_shape[1],
                          replica_id, rec.name)
                return False
        rec.next_replica_ord += 1
        # Everything fallible between the reservation and the record
        # append runs under this try: a spawn failure (head blip, bad
        # actor options) must hand the sub-slice back, or the chips
        # stay stranded until the hosting node dies (the reservation
        # has no other owner yet — graftlint: resource-leak-path).
        try:
            actor_cls = ray_tpu.remote(ReplicaActor)
            opts = dict(rec.cfg.get("actor_options") or {})
            opts.setdefault("max_concurrency",
                            rec.cfg.get("max_ongoing_requests", 8))
            init_kwargs = rec.init_kwargs
            if sub is not None:
                from ray_tpu.core import resources as resmath
                from ray_tpu.core.placement import (
                    NodeAffinitySchedulingStrategy)

                # The scalar accounting half of the reservation: the
                # actor lease holds chips/slice:<id> against the hosting
                # node, so vector scheduling and the topology grid agree.
                res = dict(opts.get("resources") or {})
                for k, v in resmath.chip_resources(
                        sub["chips"], sub["slice_id"]).items():
                    res.setdefault(k, v)
                opts["resources"] = res
                opts.setdefault("scheduling_strategy",
                                NodeAffinitySchedulingStrategy(
                                    sub["nodes"][0]))
                if "mesh_shape" not in (init_kwargs or {}):
                    init_kwargs = dict(init_kwargs or {})
                    init_kwargs["mesh_shape"] = tuple(mesh_shape)
            handle = actor_cls.options(**opts).remote(
                rec.cls_blob, rec.init_args, init_kwargs,
                replica_id=replica_id, owner_epoch=self._epoch,
                role=rec.cfg.get("role") or "")
        except Exception:
            if sub is not None:
                self._release_reservation(sub["reservation_id"],
                                          replica_id)
            raise
        rec.replicas.append(ReplicaRecord(handle, replica_id, sub))
        if sub is not None:
            try:
                # Advisory push (fire-and-forget): the replica reports
                # its sub-slice back through replica_metrics.
                handle.set_topology.remote(sub)
            except Exception:
                log_every("serve.set_topology", 10.0, logger,
                          "pushing sub-slice to replica %s failed",
                          replica_id, exc_info=True)
        if rec.cfg.get("queue_max_override"):
            try:
                # A live shed-tenant override outlives the replicas it
                # was first pushed to: respawns get it too, or the heal
                # path would quietly undo the admission clamp.
                handle.set_admission.remote(
                    int(rec.cfg["queue_max_override"]))
            except Exception:
                log_every("serve.set_admission", 10.0, logger,
                          "pushing admission cap to replica %s failed",
                          replica_id, exc_info=True)
        return True

    def _remove_replica(self, rec: DeploymentRecord,
                        index: int = -1) -> ReplicaRecord:
        """Pop a replica record. Killing the actor is the caller's job —
        via _kill_replica, outside any held lock."""
        return rec.replicas.pop(index)

    def _kill_replica(self, replica: ReplicaRecord) -> None:
        try:
            ray_tpu.kill(replica.handle)
        except Exception:
            # Expected when healing replicas the cluster already declared
            # DEAD or when the head is briefly unreachable; rate-limited
            # so a systematic kill failure still surfaces.
            log_every("serve.kill_replica", 10.0, logger,
                      "kill of replica %s failed", replica.replica_id,
                      exc_info=True)
        self._release_subslice(replica)

    def _release_subslice(self, replica: ReplicaRecord) -> None:
        """Return a dead/downscaled replica's sub-slice to the topology
        view (idempotent; a leaked reservation would strand its chips
        until the hosting node dies)."""
        sub = replica.sub_slice
        if sub is None:
            return
        replica.sub_slice = None
        self._release_reservation(sub["reservation_id"],
                                  replica.replica_id)

    def _release_reservation(self, reservation_id: str,
                             owner: str) -> None:
        """Release a reservation id, parking it for reconcile-loop
        retry when the head is unreachable — the release must
        eventually land, or the chips stay stranded."""
        from ray_tpu.core.runtime import get_core_worker

        try:
            ControllerStub(get_core_worker().controller) \
                .release_subslice(reservation_id,
                                  timeout=config.ctrl_call_timeout_s)
        except Exception:
            with self._lock:
                self._pending_releases.append(reservation_id)
            log_every("serve.release_subslice", 10.0, logger,
                      "releasing sub-slice %s of replica %s failed; "
                      "queued for retry", reservation_id, owner,
                      exc_info=True)
            # Checkpoint the queued release IMMEDIATELY: a controller
            # death between here and the retry must not leak the chips
            # until node death (the restarted controller resumes the
            # queue from the checkpoint).
            self._save_state()

    def _collect_metrics(self) -> None:
        """Snapshot-time gauges: pending sub-slice release depth (failed
        release RPCs are stranded chips until the retry succeeds) and
        the controller epoch (the doctor's controller-flapping /
        orphan-replica input)."""
        from ray_tpu.serve import metrics as smetrics

        with self._lock:
            depth = len(self._pending_releases)
        smetrics.PENDING_RELEASES.set(float(depth))
        if self._epoch > 0:
            smetrics.CONTROLLER_EPOCH.set(float(self._epoch))

    def _retry_pending_releases(self) -> None:
        """Reconcile-tick retry of release RPCs that failed (head
        blip): idempotent on the controller, so replaying an id that
        already released is harmless — including one the previous
        controller incarnation managed to release before dying."""
        with self._lock:
            if not self._pending_releases:
                return
        # Chaos hook BEFORE the queue is popped: a die/error rule here
        # kills the controller mid-release-retry with the queue intact.
        faultinject.check("serve.controller.retry_pending_releases")
        with self._lock:
            pending = self._pending_releases
            self._pending_releases = []
        from ray_tpu.core.runtime import get_core_worker

        released = 0
        for rid in pending:
            try:
                ControllerStub(get_core_worker().controller) \
                    .release_subslice(rid, timeout=config.ctrl_call_timeout_s)
                released += 1
            except Exception:
                with self._lock:
                    self._pending_releases.append(rid)
                log_every("serve.release_retry", 10.0, logger,
                          "retrying sub-slice release %s failed", rid,
                          exc_info=True)
        if released:
            # The drained ids must leave the checkpoint too: a restart
            # replaying them is harmless (idempotent) but noisy.
            self._save_state()

    def _drain(self, rec: DeploymentRecord) -> None:
        while rec.replicas:
            self._kill_replica(self._remove_replica(rec))

    def _publish(self, rec: DeploymentRecord) -> Optional[int]:
        """Push the routing snapshot (replica actor ids + model residency)
        to subscribers through the cluster pubsub (LongPollHost shape).
        Returns the published version so deploy() callers can wait for
        their own snapshot to reach their router.

        Snapshots are EPOCH-STAMPED and the hub fences them: a deposed
        zombie controller's publish is rejected server-side (and this
        instance self-fences on the rejection), and routers additionally
        ignore any snapshot whose epoch regresses below one they've
        applied."""
        from ray_tpu.core.runtime import get_core_worker

        if self._fenced:
            return None
        snapshot = {
            "epoch": self._epoch,
            "replicas": [
                {"actor_id": r.handle.actor_id.binary(),
                 "replica_id": r.replica_id,
                 "models": r.last_stats.get("models", []),
                 "prefixes": r.last_stats.get("prefixes", []),
                 # Topology in the routing snapshot: routers prefer
                 # ICI-local (same-slice) replicas without any
                 # controller round-trip on the request path.
                 "slice_id": ((r.sub_slice or {}).get("slice_id")
                              or r.last_stats.get("slice_id")),
                 "mesh_shape": r.last_stats.get("mesh_shape")}
                for r in rec.replicas],
            "max_ongoing_requests": rec.cfg.get("max_ongoing_requests", 8),
            "deleted": rec.deleting,
            # Disaggregated posture: a role="prefill" deployment's
            # routers splice requests to decode_deployment's fleet.
            # Unset reads as colocated — the legacy path, byte-for-byte.
            "role": rec.cfg.get("role") or "colocated",
            "decode_deployment": rec.cfg.get("decode_deployment"),
        }
        try:
            # min_version keeps subscriber clocks monotonic across a hub
            # (head) restart: routers long-poll with the last version they
            # saw, so a republish below it would never wake them.
            version = ControllerStub(
                get_core_worker().controller).psub_publish(
                    SNAPSHOT_CHANNEL, rec.name, snapshot,
                    rec.pub_version + 1,
                    self._epoch if self._epoch > 0 else None,
                    timeout=config.ctrl_call_timeout_s)
        except Exception:
            return None
        if version is None:
            # The hub fenced this publish: a newer epoch owns the key.
            self._fence("the snapshot hub rejected this epoch's publish")
            return None
        rec.pub_version = version
        return version

    # ----------------------------------------------------------- queries

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                name: {
                    "replicas": len(rec.replicas),
                    "replica_ids": [r.replica_id for r in rec.replicas],
                    # Disaggregated posture (colocated = legacy).
                    "role": rec.cfg.get("role") or "colocated",
                    "decode_deployment": rec.cfg.get(
                        "decode_deployment"),
                    # Handoff-lease health, summed: live (undischarged)
                    # handoffs and the payload bytes they pin. Nonzero
                    # at steady state means a leaking splice path.
                    "handoffs_live": sum(
                        r.last_stats.get("handoffs_live", 0)
                        for r in rec.replicas),
                    "handoff_live_bytes": sum(
                        r.last_stats.get("handoff_live_bytes", 0)
                        for r in rec.replicas),
                    "ongoing": sum(
                        r.last_stats.get("ongoing", 0)
                        for r in rec.replicas),
                    "load": sum(
                        max(r.last_stats.get("ongoing", 0),
                            r.last_stats.get("load", 0))
                        for r in rec.replicas),
                    # Degradation counters (replica-reported, summed):
                    # shedding/cancellation/deadline expiry show up in
                    # serve.status() AS the overload happens, not after.
                    "shed": sum(r.last_stats.get("shed", 0)
                                for r in rec.replicas),
                    "cancelled": sum(r.last_stats.get("cancelled", 0)
                                     for r in rec.replicas),
                    "deadline_exceeded": sum(
                        r.last_stats.get("deadline_exceeded", 0)
                        for r in rec.replicas),
                    # Page-pool health (paged decode replicas): free /
                    # prefix-pinned pages and prefill-backlog tokens sum
                    # across replicas; fragmentation reports the WORST
                    # replica (it is a ratio — summing is meaningless).
                    "pages_free": sum(r.last_stats.get("pages_free", 0)
                                      for r in rec.replicas),
                    "pages_pinned": sum(
                        r.last_stats.get("pages_pinned", 0)
                        for r in rec.replicas),
                    "kv_fragmentation": max(
                        (r.last_stats.get("kv_fragmentation", 0.0)
                         for r in rec.replicas), default=0.0),
                    "prefill_backlog_tokens": sum(
                        r.last_stats.get("prefill_backlog_tokens", 0)
                        for r in rec.replicas),
                    "preempted": sum(r.last_stats.get("preempted", 0)
                                     for r in rec.replicas),
                    # Topology: total chips this deployment occupies
                    # (a (2,4)-mesh replica counts 8, a single-chip
                    # replica 1) and each replica's mesh footprint +
                    # sub-slice assignment — serve.status() shows WHERE
                    # every model-parallel replica lives.
                    "chips_in_use": sum(
                        r.last_stats.get("chips",
                                         (r.sub_slice or {}).get("chips",
                                                                 1))
                        for r in rec.replicas),
                    "replica_topology": [
                        {"replica_id": r.replica_id,
                         "role": rec.cfg.get("role") or "colocated",
                         "mesh_shape": r.last_stats.get("mesh_shape"),
                         "chips": r.last_stats.get(
                             "chips",
                             (r.sub_slice or {}).get("chips", 1)),
                         "slice_id": ((r.sub_slice or {}).get("slice_id")
                                      or r.last_stats.get("slice_id")),
                         "sub_slice": ({
                             "origin": r.sub_slice["origin"],
                             "shape": r.sub_slice["shape"],
                         } if r.sub_slice else None)}
                        for r in rec.replicas],
                }
                for name, rec in self._deployments.items()
            }

    def timelines(self) -> Dict[str, Any]:
        """Engine step timelines of every replica, keyed deployment ->
        replica_id (``ray_tpu timeline --serve`` merges them into the
        cross-process Chrome trace). Bounded per-replica RPCs OUTSIDE
        the controller lock; unreachable replicas report empty."""
        with self._lock:
            recs = {name: list(rec.replicas)
                    for name, rec in self._deployments.items()}
        out: Dict[str, Any] = {}
        for name, replicas in recs.items():
            dep = out.setdefault(name, {})
            refs = [(r, r.handle.engine_timeline.remote())
                    for r in replicas]
            for replica, ref in refs:
                try:
                    dep[replica.replica_id] = ray_tpu.get(ref,
                                                          timeout=10.0)
                except Exception:
                    log_every("serve.timelines", 30.0, logger,
                              "timeline dump from replica %s failed",
                              replica.replica_id, exc_info=True)
                    dep[replica.replica_id] = {"rows": []}
        return out

    def proxy_status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                n: {"addr": p.addr, "failures": p.failures}
                for n, p in self._proxies.items()
            }

    def set_route(self, prefix: str, name: str) -> None:
        """Register an HTTP route prefix for an application (reference:
        route_prefix in serve deployments; the proxy resolves by longest
        matching prefix). REPLACES the app's previous routes so redeploys
        with a new prefix converge; prefixes normalize to a leading
        slash (a slash-less YAML value would otherwise never match)."""
        prefix = "/" + prefix.strip("/")
        with self._lock:
            self._routes = {p: n for p, n in self._routes.items()
                            if n != name}
            self._routes[prefix] = name
        self._save_state()

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._routes)

    def delete(self, name: str) -> None:
        with self._lock:
            # Route purge + tombstone atomically: a concurrent redeploy
            # can't leave a route pointing at a doomed record. The
            # record STAYS in _deployments (deleting=True) until the
            # drain finishes — so the tombstone checkpoint below still
            # knows the replicas, and a controller death mid-drain
            # restores a record it finishes killing instead of
            # orphaning live replica actors nobody reconciles.
            self._routes = {p: n for p, n in self._routes.items()
                            if n != name}
            rec = self._deployments.get(name)
            if rec is not None:
                rec.deleting = True  # under lock: reconcile must not heal it
        self._save_state()  # tombstone first, then drain
        if rec is not None:
            self._drain(rec)
            with self._lock:
                # Identity-guarded pop: a redeploy racing the drain owns
                # the name now; only remove OUR tombstoned record.
                if self._deployments.get(name) is rec:
                    del self._deployments[name]
            self._publish(rec)
            self._last_models.pop(name, None)
            self._save_state()

    def shutdown(self, drain_timeout_s: float = 10.0) -> None:
        self._stop.set()
        # Ingress first: drain proxies so in-flight requests finish against
        # still-live replicas (reference: proxy draining on serve shutdown).
        self.disable_http(drain_timeout_s)
        with self._lock:
            names = list(self._deployments)
        for name in names:
            self.delete(name)
        # The final checkpoint is EMPTY state: a controller created
        # after a deliberate shutdown must start fresh, not adopt the
        # ghosts of a torn-down serve plane.
        self._save_state()

    # -------------------------------------------------- HTTP data plane

    def enable_http(self, host: str = "127.0.0.1",
                    port: int = 0) -> Dict[str, Any]:
        """Turn on per-node HTTP ingress. Returns the current (possibly
        still-converging) state; callers poll ``http_ready`` — this actor
        runs calls serially, so blocking here would stall the whole serve
        control plane. ``port=0`` = ephemeral per proxy (required for the
        multi-node-in-one-machine fixture; on real multi-host clusters a
        fixed port works like the reference's :8000)."""
        with self._lock:
            self._http_cfg = {"host": host, "port": port}
        self._save_state()
        # Convergence belongs to the 1 Hz _proxy_loop thread — doing it
        # here would hold this serially-executed actor (and thus every
        # deploy/status/get_routes call) hostage to slow proxy starts.
        return self.http_ready()

    def http_ready(self) -> Dict[str, Any]:
        """{addrs, want}: live proxy addresses and the number of alive
        nodes they should eventually cover (0 = membership unknown)."""
        alive = self._alive_nodes()
        return {"addrs": self.http_addresses(),
                "want": len(alive) if alive is not None else 0}

    def disable_http(self, drain_timeout_s: float = 10.0) -> None:
        with self._lock:
            self._http_cfg = None
            proxies = list(self._proxies.values())
            self._proxies.clear()
        self._save_state()
        # Drain all proxies CONCURRENTLY: serial drains would make this
        # call's latency scale with node count past the caller's timeout.
        drains = [(p, p.handle.drain.remote(drain_timeout_s))
                  for p in proxies]
        deadline = time.monotonic() + drain_timeout_s + 10.0
        for proxy, ref in drains:
            try:
                ray_tpu.get(ref, timeout=max(0.1,
                                             deadline - time.monotonic()))
            except Exception:
                log_every("serve.proxy_drain", 10.0, logger,
                          "proxy %s drain did not complete",
                          proxy.node_hex, exc_info=True)
            try:
                ray_tpu.kill(proxy.handle)
            except Exception:
                log_every("serve.proxy_kill", 10.0, logger,
                          "kill of proxy %s failed", proxy.node_hex,
                          exc_info=True)

    def http_addresses(self) -> Dict[str, tuple]:
        """node hex -> (host, port) of its live proxy."""
        with self._lock:
            return {n: p.addr for n, p in self._proxies.items()
                    if p.addr is not None}

    def _alive_nodes(self) -> Optional[List[str]]:
        """None = membership UNKNOWN (head unreachable / just restarted).
        Callers must treat unknown as "change nothing" — tearing down
        proxies on a head blip would sever live ingress cluster-wide."""
        from ray_tpu.core.runtime import get_core_worker

        try:
            nodes = ControllerStub(
                get_core_worker().controller).list_nodes(
                    timeout=config.ctrl_call_timeout_s)
        except Exception:
            return None
        alive = [n["node_id"] for n in nodes if n["alive"]]
        return alive or None  # an empty table = restarted head, same rule

    def _reconcile_proxies(self) -> None:
        """Converge proxies with node membership (reference:
        proxy_state.py ProxyStateManager.update): start one on every new
        alive node, health-check existing ones, replace the dead, drain
        and remove proxies on departed nodes."""
        with self._lock:
            cfg = self._http_cfg
        if cfg is None:
            return
        alive_list = self._alive_nodes()
        if alive_list is None:
            return  # membership unknown: change nothing
        alive = set(alive_list)
        with self._lock:
            current = dict(self._proxies)
            before = {n: p.handle.actor_id
                      for n, p in self._proxies.items()}
        # Departed nodes: drain what's left of the proxy, forget it.
        for node_hex, proxy in current.items():
            if node_hex not in alive:
                with self._lock:
                    self._proxies.pop(node_hex, None)
                try:
                    ray_tpu.kill(proxy.handle)
                except Exception:
                    # Departed node: the actor is usually already gone.
                    log_every("serve.proxy_kill", 10.0, logger,
                              "kill of proxy %s failed", node_hex,
                              exc_info=True)
        # Health-check live ones (the actor call doubles as the probe).
        for node_hex, proxy in current.items():
            if node_hex not in alive:
                continue
            try:
                health = ray_tpu.get(proxy.handle.healthz.remote(),
                                     timeout=5.0)
                proxy.addr = tuple(health["addr"])
                proxy.failures = 0
            except Exception:
                proxy.failures += 1
                if proxy.failures < 3:
                    continue
                # Only replace a proxy the cluster declares DEAD — a slow
                # one still owns its port/socket.
                from ray_tpu.core.runtime import get_core_worker

                try:
                    record = ControllerStub(
                        get_core_worker().controller).get_actor(
                            proxy.handle.actor_id.binary(),
                            timeout=config.ctrl_call_timeout_s)
                except Exception:
                    # Actor table unreachable: we can neither verify nor
                    # replace (starting a proxy needs the head too), so
                    # keep the record and retry next round — the normal
                    # paths below take over the moment the head answers.
                    continue
                if record is not None and record["state"] != "DEAD":
                    # Alive-but-unresponsive (healthz failing for many
                    # rounds while the actor table says ALIVE — a hung
                    # proxy): force-kill it, but DON'T forget the handle
                    # yet. Proxies bind a fixed ingress port, so the
                    # record may only be dropped once a later round
                    # observes DEAD — popping a live process would
                    # EADDRINUSE every replacement.
                    if proxy.failures >= 10:
                        try:
                            ray_tpu.kill(proxy.handle)
                        except Exception:
                            log_every("serve.proxy_kill", 10.0, logger,
                                      "kill of hung proxy %s failed",
                                      node_hex, exc_info=True)
                    continue
                # No record, or DEAD: safe to forget and let the
                # missing-node pass below start a replacement.
                with self._lock:
                    if self._proxies.get(node_hex) is proxy:
                        self._proxies.pop(node_hex)
        # Missing nodes: start a proxy pinned to that node.
        with self._lock:
            have = set(self._proxies)
        for node_hex in alive - have:
            try:
                self._start_proxy(node_hex, cfg)
            except Exception:
                # A node with no proxy has no ingress — this must never
                # fail invisibly (retried next round either way).
                log_every("serve.proxy_start", 5.0, logger,
                          "starting proxy on node %s failed", node_hex,
                          exc_info=True)
        with self._lock:
            after = {n: p.handle.actor_id
                     for n, p in self._proxies.items()}
        if after != before:
            # Proxy membership changed: checkpoint so a restarted
            # controller adopts the live proxies instead of binding
            # duplicates next to them (EADDRINUSE on fixed ports).
            self._save_state()

    def _start_proxy(self, node_hex: str, cfg: Dict[str, Any]) -> None:
        from ray_tpu.core.placement import NodeAffinitySchedulingStrategy
        from ray_tpu.serve.proxy import ProxyActor

        actor_cls = ray_tpu.remote(ProxyActor)
        handle = actor_cls.options(
            num_cpus=0,
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_hex),
            max_concurrency=8,
        ).remote(cfg["host"], cfg["port"])
        proxy = ProxyRecord(node_hex, handle)
        with self._lock:
            raced = node_hex in self._proxies or self._http_cfg is None
            if not raced:
                self._proxies[node_hex] = proxy
        if raced:  # raced another reconcile/disable; kill OUTSIDE the lock
            try:
                ray_tpu.kill(handle)
            except Exception:
                log_every("serve.proxy_kill", 10.0, logger,
                          "kill of raced proxy on %s failed", node_hex,
                          exc_info=True)
            return
        try:
            proxy.addr = tuple(ray_tpu.get(
                handle.address.remote(), timeout=30.0))
        except Exception:
            proxy.failures += 1

    # --------------------------------------------------------- reconcile

    def _reconcile_loop(self) -> None:
        while not self._stop.wait(0.25):
            # Chaos hook: a die rule here SIGKILLs the controller at a
            # deterministic point in its duty cycle (the canonical
            # "controller death is a non-event" injection).
            faultinject.check("serve.controller.reconcile_tick")
            if self._epoch <= 0:
                # Epoch lease was unavailable at start: keep trying —
                # until it lands, publishes are unfenced and nothing
                # checkpoints.
                self._acquire_epoch()
                if self._epoch > 0:
                    self._save_state()
            try:
                self._retry_pending_releases()
            except Exception:
                log_every("serve.release_retry_pass", 10.0, logger,
                          "pending-release retry pass failed",
                          exc_info=True)
            with self._lock:
                recs = list(self._deployments.values())
            for rec in recs:
                try:
                    self._reconcile_one(rec)
                except Exception:
                    # The loop must survive one bad record, but a
                    # reconcile that fails every tick is an outage
                    # (replicas not healing) — say so, rate-limited.
                    log_every(f"serve.reconcile.{rec.name}", 5.0, logger,
                              "reconcile of deployment %r failed",
                              rec.name, exc_info=True)

    def _proxy_loop(self) -> None:
        # Membership changes are rare; 1 Hz keeps probe load low.
        while not self._stop.wait(1.0):
            try:
                self._reconcile_proxies()
            except Exception:
                log_every("serve.proxy_reconcile", 5.0, logger,
                          "proxy reconcile pass failed", exc_info=True)

    def _stale(self, rec: DeploymentRecord) -> bool:
        with self._lock:
            return (rec.deleting
                    or self._deployments.get(rec.name) is not rec)

    def _reconcile_one(self, rec: DeploymentRecord) -> None:
        """Collect replica stats, replace dead replicas, autoscale
        (reference: DeploymentState.update + autoscaling_policy.py:12).
        Every mutation re-validates the record is still live (_stale) so a
        concurrent redeploy/delete can't be resurrected; structural changes
        hold rec.lock so deploy's settle can't race a double-add."""
        if self._stale(rec):
            return
        changed = False
        stats_refs = [(r, r.handle.stats.remote()) for r in rec.replicas]
        suspect: List[ReplicaRecord] = []
        for replica, ref in stats_refs:
            try:
                replica.last_stats = ray_tpu.get(ref, timeout=5.0)
            except Exception:
                suspect.append(replica)
        # A slow stats reply is NOT death: a replica still initializing or
        # saturated must not be dropped (and certainly not leaked). Only
        # replicas whose ACTOR the cluster declares DEAD are replaced.
        dead = []
        for replica in suspect:
            try:
                from ray_tpu.core.runtime import get_core_worker

                record = ControllerStub(
                    get_core_worker().controller).get_actor(
                        replica.handle.actor_id.binary(),
                        timeout=config.ctrl_call_timeout_s)
            except Exception:
                continue
            if record is None or record["state"] == "DEAD":
                dead.append(replica)
        if self._stale(rec):
            return
        to_kill: List[ReplicaRecord] = []
        with rec.lock:
            if self._stale(rec):
                return
            for replica in dead:
                try:
                    rec.replicas.remove(replica)
                except ValueError:
                    continue
                to_kill.append(replica)
                changed = True
            while (len(rec.replicas) < self._min_replicas(rec)
                   and not self._stale(rec)):
                if not self._add_replica(rec):
                    break  # unplaceable (no contiguous sub-slice): retry
                changed = True  # next tick, never spawn on a fragment
        # Idempotent cleanup kills happen after rec.lock is released —
        # an RPC under the record lock would stall deploy/settle on this
        # deployment (graftlint: lock-held-blocking).
        for replica in to_kill:
            self._kill_replica(replica)
        if self._stale(rec):
            self._drain(rec)  # raced a delete after adding: clean up
            return

        auto = rec.cfg.get("autoscaling")
        if auto:
            downscaled: Optional[ReplicaRecord] = None
            with rec.lock:
                # Replica load = max(HTTP concurrency, replica-reported
                # backlog): a decode engine with a full pending queue and
                # every slot busy must scale OUT even when each request
                # occupies only one "ongoing" call slot. autoscale_load
                # additionally inflates speculative replicas' signal by
                # their verify overhead at the observed accept rate, so
                # spec engines don't over-report headroom.
                ongoing = sum(autoscale_load(r.last_stats)
                              for r in rec.replicas)
                # A mesh-parallel replica is chips-many units of
                # capacity, not one: load per CHIP drives the count, so
                # an 8-chip replica absorbs 8x the target before a
                # second replica (and its whole sub-slice) spawns.
                cap = max(1e-9, auto["target_ongoing_requests"]
                          * self._mesh_chips(rec))
                desired = max(auto["min_replicas"],
                              min(auto["max_replicas"],
                                  math.ceil(ongoing / cap)))
                now = time.monotonic()
                if (desired > len(rec.replicas)
                        and now - rec.last_scale > auto["upscale_delay_s"]):
                    if self._add_replica(rec):
                        rec.last_scale = now
                        changed = True
                elif (desired < len(rec.replicas)
                        and now - rec.last_scale >
                        auto["downscale_delay_s"]):
                    downscaled = self._remove_replica(rec)
                    rec.last_scale = now
                    changed = True
            if downscaled is not None:
                self._kill_replica(downscaled)
        # Model residency changes also need a push (multiplex routing).
        if changed:
            self._publish(rec)
            # Structural change (replica healed/scaled): checkpoint so a
            # controller death right now restores THIS replica set.
            self._save_state()
        elif self._models_changed(rec):
            self._publish(rec)
        elif rec.pub_version:
            # Head-restart healing: a restarted cluster controller comes
            # back with an EMPTY pubsub hub, so routers created after the
            # restart would find no snapshot. Periodically compare the
            # hub's current version with what we last published and
            # republish on regression.
            now = time.monotonic()
            if now - rec.last_pub_check > 2.0:
                rec.last_pub_check = now
                try:
                    from ray_tpu.core.runtime import get_core_worker

                    cur = ControllerStub(
                        get_core_worker().controller).psub_poll(
                            SNAPSHOT_CHANNEL, rec.name, 0, 0.0,
                            timeout=5.0)
                except Exception:
                    cur = rec.pub_version  # unreachable hub: not a reset
                if cur is None or (isinstance(cur, tuple)
                                   and (cur[0] < rec.pub_version
                                        or (isinstance(cur[1], dict)
                                            and cur[1].get(
                                                "epoch", self._epoch)
                                            < self._epoch))):
                    # Version regression (hub restarted empty) OR epoch
                    # regression (a zombie's stamp survives on the hub
                    # — possible only in the pre-fencing window): either
                    # way this epoch's snapshot must own the key again.
                    self._publish(rec)

    def _min_replicas(self, rec: DeploymentRecord) -> int:
        auto = rec.cfg.get("autoscaling")
        return (auto["min_replicas"] if auto
                else rec.cfg.get("num_replicas", 1))

    def _models_changed(self, rec: DeploymentRecord) -> bool:
        """Model OR prefix residency drift: both route affinity, so both
        need a snapshot push when they change."""
        cur = {r.replica_id: (tuple(r.last_stats.get("models", [])),
                              tuple(sorted(r.last_stats.get("prefixes",
                                                            []))))
               for r in rec.replicas}
        if self._last_models.get(rec.name) != cur:
            self._last_models[rec.name] = cur
            return True
        return False

    def ping(self) -> str:
        return "pong"


def get_or_create_controller():
    """Resolve (or start) the cluster's serve controller actor."""
    from ray_tpu.core.errors import ActorDiedError, ActorUnavailableError

    try:
        handle = ray_tpu.get_actor(CONTROLLER_NAME)
        try:
            ray_tpu.get(handle.ping.remote(), timeout=30.0)
        except ActorUnavailableError:
            # One retry on the SAME handle. A fresh handle hints
            # incarnation 0, so its first call to a RESTARTED
            # (max_restarts=-1) controller always fails — and the
            # failure taught the handle the live incarnation. When the
            # controller is genuinely down, attempt 1 doubled as the
            # failure report that triggers its restart, and this retry
            # parks until the restarted incarnation is ALIVE — callers
            # resume against the recovered control plane.
            ray_tpu.get(handle.ping.remote(), timeout=30.0)
        return handle
    except (ValueError, ActorDiedError, ActorUnavailableError):
        pass  # absent or dead: (re)create — name registration allows
        # replacing a DEAD actor.
    actor_cls = ray_tpu.remote(ServeController)
    try:
        handle = actor_cls.options(name=CONTROLLER_NAME, num_cpus=0,
                                   max_restarts=-1).remote()
        ray_tpu.get(handle.ping.remote(), timeout=60.0)
        return handle
    except Exception:
        # Raced with another creator: the named actor exists now.
        return ray_tpu.get_actor(CONTROLLER_NAME)
