"""Host-side prefix KV-cache index for the decode serving plane.

vLLM/SGLang-style prefix caching, adapted to this repo's static-bucket
TPU engine (``serve/decode.py``): requests that share a prompt prefix
(system prompts, few-shot templates, RL rollout generation) should pay
prefill only for their uncached SUFFIX. The split of responsibilities:

* THIS module is the host-side index: a token-level trie mapping cached
  prefixes to rows of a device-resident prefix pool, with refcounted LRU
  eviction and hit/saved-token accounting. It never touches device
  memory — the engine owns the pool arrays and the jitted gather/scatter
  programs that splice an entry into a request's slot.
* Entries are inserted at BUCKET-ALIGNED lengths (largest power of two
  <= the prompt length, capped at the pool's per-entry capacity) and
  deduplicated on their token key, so the compiled-program set and the
  router's affinity hash grid stay bounded.
* A match may be PARTIAL: a request sharing only the first 40 tokens of
  a 64-token entry still splices the whole entry — the suffix prefill
  overwrites positions >= 40 and the per-slot length mask hides the
  rest, so correctness never depends on the match covering the entry.

``prefix_hash``/``candidate_hashes`` are shared with the serve router:
replicas advertise the hashes of their resident entries, and routers
hash a request's leading token buckets to find the replica whose pool
already holds the prompt (prefix-affinity routing).

PAGED engines (``kv_page_tokens > 0``) use ``serve/paging.py``'s
``PagedPrefixIndex`` instead: the same trie-style longest-prefix
contract and hit/insert/evict accounting, but entries pin PAGE RANGES
of the shared KV pool (refcounted, zero-copy insert and splice,
page-granular tail eviction) rather than whole ``capacity``-sized rows
— this class remains the contiguous-mode index. Both advertise hashes
on the same power-of-two grid, so the router is mode-agnostic.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


def prefix_hash(tokens) -> str:
    """Stable short hash of a token-id sequence (router <-> replica
    affinity key; also the pool's dedup identity)."""
    arr = np.ascontiguousarray(np.asarray(tokens, np.int32))
    return hashlib.blake2b(arr.tobytes(), digest_size=8).hexdigest()


def bucket_lengths(n: int, min_tokens: int,
                   cap: Optional[int] = None) -> List[int]:
    """Power-of-two prefix lengths <= n (>= min_tokens, <= cap),
    DESCENDING — the grid on which entries are inserted and affinity
    hashes computed."""
    out: List[int] = []
    b = 1
    while b * 2 <= n:
        b *= 2
    while b >= max(1, min_tokens):
        if cap is None or b <= cap:
            out.append(b)
        b //= 2
    return out


def candidate_hashes(tokens, min_tokens: int,
                     cap: Optional[int] = None) -> List[str]:
    """Hashes of a prompt's leading buckets, longest first: the router
    probes these against replicas' advertised prefix sets."""
    toks = np.asarray(tokens, np.int32).reshape(-1)
    return [prefix_hash(toks[:b])
            for b in bucket_lengths(len(toks), min_tokens, cap)]


class _Node:
    __slots__ = ("children", "count", "entry")

    def __init__(self):
        self.children: Dict[int, "_Node"] = {}
        self.count = 0                 # entries terminating in this subtree
        self.entry: Optional[int] = None  # pool row terminating HERE


@dataclass
class _Entry:
    row: int                 # pool row holding this prefix's K/V
    tokens: np.ndarray       # the cached token prefix, (length,)
    length: int
    key_hash: str
    refcount: int = 0        # in-flight splices pinning the row
    last_used: int = 0       # logical LRU clock


class PrefixCache:
    """Trie index over token-id prefixes -> refcounted pool rows.

    ``entries`` pool rows of up to ``capacity`` tokens each. ``match``
    ACQUIRES the returned entry (the caller releases after the splice is
    dispatched); eviction only ever picks rows with refcount == 0, so a
    row can never be recycled under an in-flight splice."""

    def __init__(self, entries: int, capacity: int, min_tokens: int = 16):
        self.capacity = int(capacity)
        self.min_tokens = max(1, int(min_tokens))
        self._root = _Node()
        self._entries: Dict[int, _Entry] = {}
        self._free: List[int] = list(range(int(entries)))
        self._clock = 0
        self.queries = 0
        self.hits = 0
        self.tokens_matched = 0  # prefill tokens saved by splicing
        self.inserts = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    # ----------------------------------------------------------- match

    def match(self, tokens) -> Optional[Tuple[int, int]]:
        """Longest cached-prefix match: ``(entry_row, matched_len)`` with
        the entry acquired (caller MUST ``release``), or None. The match
        is capped at ``len(tokens) - 1``: at least one real suffix token
        must remain to produce next-token logits."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        self.queries += 1
        limit = min(len(toks) - 1, self.capacity)
        node = self._root
        depth = 0
        while depth < limit:
            child = node.children.get(int(toks[depth]))
            if child is None or child.count == 0:
                break
            node = child
            depth += 1
        if depth < self.min_tokens or node is self._root:
            return None
        row = self._find_entry(node)
        if row is None:
            return None
        ent = self._entries[row]
        ent.refcount += 1
        self._clock += 1
        ent.last_used = self._clock
        self.hits += 1
        self.tokens_matched += depth
        return row, depth

    def _find_entry(self, node: _Node) -> Optional[int]:
        """Any entry in ``node``'s subtree: every entry below shares the
        walked prefix, and the splice + suffix overwrite makes them all
        equally correct donors."""
        while node.entry is None:
            for child in node.children.values():
                if child.count > 0:
                    node = child
                    break
            else:
                return None
        return node.entry

    def release(self, row: int) -> None:
        ent = self._entries.get(row)
        if ent is not None and ent.refcount > 0:
            ent.refcount -= 1

    # ---------------------------------------------------------- insert

    def insert(self, tokens,
               matched_len: int = 0) -> Optional[Tuple[int, int]]:
        """Offer a completed prompt to the pool. Returns ``(row,
        insert_len)`` — the caller must then copy the slot's first
        ``capacity`` cache positions into pool row ``row`` — or None
        (prefix too short, already cached, covered, or every row is
        pinned). ``insert_len`` is bucket-aligned (largest power of two
        <= the prompt length).

        ``matched_len`` is the prompt's own prefix-cache match at
        admission: inserting is skipped unless it would at least DOUBLE
        the cached coverage for this prompt. Without this, a hot shared
        prefix followed by per-request random suffixes inserts a
        distinct (never-deduped) entry per request — a device copy per
        admission plus pool thrash that costs more than the cache saves."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        lens = bucket_lengths(len(toks), self.min_tokens, self.capacity)
        if not lens:
            return None
        ins_len = lens[0]
        if matched_len * 2 >= ins_len:
            return None
        key = toks[:ins_len]
        node = self._root
        for t in key:
            child = node.children.get(int(t))
            if child is None:
                break
            node = child
        else:
            if node.entry is not None:  # dedup: refresh recency only
                ent = self._entries[node.entry]
                self._clock += 1
                ent.last_used = self._clock
                return None
        row = self._alloc_row()
        if row is None:
            return None
        ent = _Entry(row, np.array(key, np.int32), ins_len,
                     prefix_hash(key))
        self._clock += 1
        ent.last_used = self._clock
        self._entries[row] = ent
        node = self._root
        for t in key:
            child = node.children.get(int(t))
            if child is None:
                child = _Node()
                node.children[int(t)] = child
            child.count += 1
            node = child
        node.entry = row
        self.inserts += 1
        return row, ins_len

    def _alloc_row(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        victim: Optional[_Entry] = None
        for ent in self._entries.values():
            if ent.refcount == 0 and (victim is None
                                      or ent.last_used < victim.last_used):
                victim = ent
        if victim is None:
            return None  # every row pinned by an in-flight splice
        self._evict(victim)
        return victim.row

    def _evict(self, ent: _Entry) -> None:
        node = self._root
        for t in ent.tokens:
            child = node.children[int(t)]
            child.count -= 1
            if child.count == 0:
                del node.children[int(t)]
                break
            node = child
        else:
            node.entry = None
        del self._entries[ent.row]
        self.evictions += 1

    # ----------------------------------------------------------- stats

    def hashes(self) -> List[str]:
        """Resident entry hashes, for replica advertisement. Called from
        the replica's stats thread while the decode thread mutates the
        index: list() snapshots the dict atomically under the GIL."""
        return [ent.key_hash for ent in list(self._entries.values())]

    def stats(self) -> Dict[str, float]:
        return {
            "entries": len(self._entries),
            "queries": self.queries,
            "hits": self.hits,
            "hit_rate": round(self.hits / self.queries, 4)
            if self.queries else 0.0,
            "prefill_tokens_saved": self.tokens_matched,
            "inserts": self.inserts,
            "evictions": self.evictions,
        }
