"""Serve-plane SLO instruments + the one summary the surfaces share.

The SLOs TPU serving is judged by are latency DISTRIBUTIONS — TTFT and
time-per-output-token — not point gauges ("Fine-Tuning and Serving
Gemma on Cloud TPU", PAPERS.md). This module registers them as
``util.metrics`` Counter/Histogram instruments labeled by deployment,
recorded by the decode engine / router / proxy per REQUEST (never per
token or per step — the decode loop must not pay a registry lock per
step), flushed through the existing per-process metrics flusher to the
cluster controller, and read back identically by:

* the HTTP proxy's ``/metrics`` route (Prometheus exposition text),
* ``serve.status()``'s per-deployment ``slo`` summaries,
* the dashboard's serve panel,
* ``bench_serve.py`` / ``bench_decode.py`` percentile rows.

One registry, one aggregation path (``slo_summary``), one answer.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.util.metrics import (Counter, Gauge, Histogram, counter_totals,
                                  histogram_summary, merge_histograms)

# Latency grids sized for decode serving: TTFT spans admission-queue
# waits (ms) through multi-second prefill backlogs; inter-token spans
# sub-ms TPU steps through seconds of CPU-host steps.
_TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
_TOKEN_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                  0.1, 0.25, 0.5, 1.0, 2.5)
_HTTP_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                 5.0, 10.0, 30.0, 60.0)

TTFT = Histogram(
    "serve_ttft_s",
    "Time to first token: engine submit -> first emitted token "
    "(includes queue wait and prefill).",
    boundaries=_TTFT_BUCKETS, tag_keys=("deployment",))

INTER_TOKEN = Histogram(
    "serve_inter_token_s",
    "Per-output-token latency of one request's stream: (finish - first "
    "token) / (tokens - 1), observed once per completed request "
    "(robust to chunked emission's bursty raw gaps).",
    boundaries=_TOKEN_BUCKETS, tag_keys=("deployment",))

QUEUE_WAIT = Histogram(
    "serve_queue_wait_s",
    "Engine admission-queue wait: submit -> prefill dispatch.",
    boundaries=_TTFT_BUCKETS, tag_keys=("deployment",))

HTTP_LATENCY = Histogram(
    "serve_http_request_s",
    "HTTP proxy request latency (headers in -> response written), "
    "labeled by resolved deployment.",
    boundaries=_HTTP_BUCKETS, tag_keys=("deployment",))

REQUESTS = Counter(
    "serve_requests_total",
    "Engine request outcomes: completed | cancelled | deadline_exceeded "
    "| shed | error.",
    tag_keys=("deployment", "outcome"))

HTTP_REQUESTS = Counter(
    "serve_http_requests_total",
    "HTTP proxy responses by status code.",
    tag_keys=("deployment", "code"))

RETRIES = Counter(
    "serve_router_retries_total",
    "Router retries after replica death (attempts beyond the first).",
    tag_keys=("deployment",))

PREEMPTIONS = Counter(
    "serve_preemptions_total",
    "Engine recompute-preemptions under page pressure.",
    tag_keys=("deployment",))

# Speculative decoding: proposal volume + acceptance. Counters give the
# fleet-wide accepted/proposed ratio (the speedup predictor); the
# histogram gives the per-request distribution (a bimodal accept rate
# means one traffic class defeats the draft). Observed once per request
# at its terminal step, per the per-REQUEST doctrine above.
SPEC_PROPOSED = Counter(
    "serve_spec_proposed_tokens_total",
    "Draft tokens proposed to the verify forward.",
    tag_keys=("deployment",))

SPEC_ACCEPTED = Counter(
    "serve_spec_accepted_tokens_total",
    "Draft tokens accepted by target verification.",
    tag_keys=("deployment",))

SPEC_ACCEPT = Histogram(
    "serve_spec_accept_rate",
    "Per-request draft acceptance rate (accepted / proposed).",
    boundaries=(0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0),
    tag_keys=("deployment",))

# Disaggregated prefill/decode handoff (ROADMAP #3). Descriptor bytes
# prove the handoff rides the object plane by reference: the descriptor
# is block-table metadata (~hundreds of bytes), never the KV payload
# itself — a descriptor past a few KiB means someone inlined pages.
# Latency is publish -> adopt (the lease's open interval); the counter's
# event tag closes the books: published == adopted + aborted + expired
# at quiescence, anything else is a leaked lease.
_HANDOFF_BYTE_BUCKETS = (128.0, 256.0, 512.0, 1024.0, 2048.0, 4096.0,
                         8192.0, 16384.0, 65536.0)

HANDOFF_BYTES = Histogram(
    "serve_handoff_bytes",
    "Pickled size of one prefill->decode handoff descriptor (block-table "
    "metadata + ObjectRefs, NOT the KV payload).",
    boundaries=_HANDOFF_BYTE_BUCKETS, tag_keys=("deployment",))

HANDOFF_LATENCY = Histogram(
    "serve_handoff_latency_s",
    "KV-page handoff lease lifetime: publish on the prefill replica -> "
    "adopt acknowledged by the decode side.",
    boundaries=_TTFT_BUCKETS, tag_keys=("deployment",))

HANDOFFS = Counter(
    "serve_handoffs_total",
    "KV-page handoff lease events: published | adopted | aborted | "
    "expired. published - (adopted + aborted + expired) is the number "
    "of leases currently open; nonzero at quiescence means leaked "
    "pages/refs.",
    tag_keys=("deployment", "event"))

PENDING_RELEASES = Gauge(
    "serve_pending_subslice_releases",
    "Sub-slice release RPCs awaiting retry after a head blip "
    "(ServeController._pending_releases depth — growth means chips are "
    "stranded until the reconcile loop gets through).")

CONTROLLER_EPOCH = Gauge(
    "serve_controller_epoch",
    "Monotonic serve-controller epoch (bumped on every controller "
    "(re)start via the core epoch lease). A delta >= 2 over a doctor "
    "window means the controller is crash-looping "
    "(controller-flapping); the max across sources is the OWNING epoch "
    "replicas are checked against.")

REPLICA_EPOCH = Gauge(
    "serve_replica_epoch",
    "The controller epoch that owns this replica (assigned at spawn, "
    "re-pushed at adoption). A replica whose epoch stays below the "
    "live controller epoch — or that reports with no controller series "
    "at all — is serving traffic nobody reconciles (orphan-replica).",
    tag_keys=("deployment",))

# Outcomes worth a counter key even at zero; keeps dashboards stable.
OUTCOMES = ("completed", "cancelled", "deadline_exceeded", "shed", "error")

_HISTOGRAMS = {
    "ttft_s": "serve_ttft_s",
    "inter_token_s": "serve_inter_token_s",
    "queue_wait_s": "serve_queue_wait_s",
    "http_request_s": "serve_http_request_s",
    "spec_accept_rate": "serve_spec_accept_rate",
    "handoff_bytes": "serve_handoff_bytes",
    "handoff_latency_s": "serve_handoff_latency_s",
}


def slo_summary(aggregated: Dict[str, List[Dict[str, Any]]]
                ) -> Dict[str, Dict[str, Any]]:
    """Per-deployment SLO view from the controller's aggregated metrics
    (``list_metrics``): histogram summaries (count/mean/p50/p99) for
    TTFT, inter-token, queue-wait and HTTP latency, plus outcome /
    retry / preemption counter totals. The single source of truth
    behind ``serve.status()``, the dashboard serve panel and the bench
    percentile rows."""
    out: Dict[str, Dict[str, Any]] = {}

    def rec(deployment: str) -> Dict[str, Any]:
        return out.setdefault(deployment, {})

    for field, name in _HISTOGRAMS.items():
        for key, entry in merge_histograms(aggregated, name).items():
            dep = dict(key).get("deployment", "-")
            rec(dep)[field] = histogram_summary(entry)
    for key, total in counter_totals(aggregated,
                                     "serve_requests_total").items():
        tags = dict(key)
        dep = tags.get("deployment", "-")
        rec(dep).setdefault("outcomes", {})[
            tags.get("outcome", "?")] = int(total)
    for name, field in (("serve_router_retries_total", "retries"),
                        ("serve_preemptions_total", "preempted"),
                        ("serve_spec_proposed_tokens_total",
                         "spec_proposed_tokens"),
                        ("serve_spec_accepted_tokens_total",
                         "spec_accepted_tokens"),
                        ("serve_http_requests_total", "http_responses")):
        for key, total in counter_totals(aggregated, name).items():
            tags = dict(key)
            dep = tags.get("deployment", "-")
            if name == "serve_http_requests_total":
                rec(dep).setdefault(field, {})[
                    tags.get("code", "?")] = int(total)
            else:
                rec(dep)[field] = rec(dep).get(field, 0) + int(total)
    for key, total in counter_totals(aggregated,
                                     "serve_handoffs_total").items():
        tags = dict(key)
        dep = tags.get("deployment", "-")
        rec(dep).setdefault("handoffs", {})[
            tags.get("event", "?")] = int(total)
    return out
