"""Deployments + handles + routing (data plane).

Analogue of the reference's Serve data plane: ``DeploymentHandle``
(``serve/handle.py:714``) -> ``Router.assign_request`` (``router.py:312``)
-> power-of-two-choices replica picking
(``replica_scheduler/pow_2_scheduler.py:49``) -> ``ReplicaActor``. Routing
state is pushed, not polled: every handle watches the cluster pubsub for
its deployment's replica snapshot (the reference's LongPollHost pattern,
``long_poll.py:173``), so scale-ups, scale-downs, replica deaths and
multiplexed-model residency changes propagate to all routers without any
controller round-trip on the request path.

In-flight counts are client-side per handle (the sample the reference's
pow-2 scheduler uses is its own probe of its own outstanding requests per
replica); model-aware routing prefers replicas that already have the
requested ``multiplexed_model_id`` loaded (``serve/multiplex.py``).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core.actor import ActorHandle
from ray_tpu.core.errors import (ActorDiedError, ActorUnavailableError,
                                 DeadlineExceededError, GetTimeoutError)
from ray_tpu.core.ids import ActorID
from ray_tpu.serve.controller import SNAPSHOT_CHANNEL
from ray_tpu.util.ratelimit import log_every

logger = logging.getLogger(__name__)


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 5.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "target_ongoing_requests": self.target_ongoing_requests,
            "upscale_delay_s": self.upscale_delay_s,
            "downscale_delay_s": self.downscale_delay_s,
        }


class Deployment:
    """Declarative deployment config (``@serve.deployment``)."""

    ROLES = (None, "colocated", "prefill", "decode")

    def __init__(self, cls, name: Optional[str] = None,
                 num_replicas: int = 1,
                 ray_actor_options: Optional[Dict] = None,
                 autoscaling_config: Optional[AutoscalingConfig] = None,
                 max_ongoing_requests: int = 8,
                 mesh_shape: Optional[Any] = None,
                 role: Optional[str] = None,
                 decode_deployment: Optional[str] = None):
        self.cls = cls
        self.name = name or cls.__name__
        self.num_replicas = num_replicas
        self.actor_options = ray_actor_options or {}
        self.autoscaling = autoscaling_config
        self.max_ongoing_requests = max_ongoing_requests
        # (batch, model) decode-mesh footprint per replica: the serve
        # controller reserves an ICI-contiguous sub-slice of that many
        # chips before spawning each replica, and the replica's engine
        # spans it with GSPMD-sharded weights/KV (single replica, many
        # devices — the model-parallel serving mode).
        self.mesh_shape = tuple(mesh_shape) if mesh_shape else None
        # Disaggregated serving posture (ROADMAP #3). Unset/"colocated"
        # is the legacy path, byte-for-byte: each replica prefills AND
        # decodes. "prefill" replicas run admission + chunked prefill,
        # publish the filled KV pages over the object plane, and the
        # router splices each request to ``decode_deployment`` (a
        # role="decode" deployment of the SAME model/page geometry),
        # which adopts the pages — zero recompute — and decodes.
        if role not in self.ROLES:
            raise ValueError(
                f"role must be one of {self.ROLES}, got {role!r}")
        if role == "prefill" and not decode_deployment:
            raise ValueError(
                "role='prefill' requires decode_deployment (the "
                "role='decode' deployment that adopts its handoffs)")
        self.role = role
        self.decode_deployment = decode_deployment
        self._init_args: tuple = ()
        self._init_kwargs: dict = {}

    def options(self, **overrides) -> "Deployment":
        dep = Deployment(self.cls, self.name, self.num_replicas,
                         dict(self.actor_options), self.autoscaling,
                         self.max_ongoing_requests, self.mesh_shape,
                         self.role, self.decode_deployment)
        dep._init_args = self._init_args
        dep._init_kwargs = self._init_kwargs
        for k, v in overrides.items():
            setattr(dep, "autoscaling" if k == "autoscaling_config"
                    else ("actor_options" if k == "ray_actor_options" else k),
                    v)
        return dep

    def bind(self, *args, **kwargs) -> "Deployment":
        self._init_args = args
        self._init_kwargs = kwargs
        return self

    def config_dict(self) -> Dict[str, Any]:
        mesh = self.mesh_shape or self._init_kwargs.get("mesh_shape")
        return {
            "num_replicas": self.num_replicas,
            "actor_options": dict(self.actor_options),
            "autoscaling": (self.autoscaling.to_dict()
                            if self.autoscaling else None),
            "max_ongoing_requests": self.max_ongoing_requests,
            # Explicit deployment-level mesh wins; a mesh_shape bound
            # into the class's init kwargs (LlamaDecodeDeployment-style)
            # reaches placement the same way.
            "mesh_shape": list(mesh) if mesh else None,
            "role": self.role,
            "decode_deployment": self.decode_deployment,
        }


def deployment(_cls=None, **kwargs):
    """``@serve.deployment`` decorator (reference: ``serve/api.py``)."""

    def wrap(cls):
        return Deployment(cls, **kwargs)

    if _cls is not None:
        return wrap(_cls)
    return wrap


def _affinity_hashes(args: tuple):
    """Candidate prefix hashes for a generation-shaped request (a dict
    with a ``tokens`` sequence as the first positional arg). Returns
    None when affinity is disabled or the request has no token prompt —
    routing then falls through to pure pow-2 least-loaded."""
    from ray_tpu.core.config import config as rt_config

    if not rt_config.prefix_affinity_enabled:
        return None
    req = args[0] if args else None
    if not isinstance(req, dict):
        return None
    tokens = req.get("tokens")
    if tokens is None:
        return None
    try:
        from ray_tpu.serve.prefix_cache import candidate_hashes

        return candidate_hashes(
            tokens, rt_config.prefix_match_min_tokens) or None
    except Exception:
        return None


def _error_chain(e: BaseException):
    """Walk an exception chain (TaskError.cause / __cause__) — replica-
    side typed errors arrive wrapped in the actor-call error shipping,
    and the splice's fallback decisions key on the original type."""
    seen = set()
    cur: Optional[BaseException] = e
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        yield cur
        nxt = getattr(cur, "cause", None)
        cur = nxt if isinstance(nxt, BaseException) else cur.__cause__


_local_slice_cache: List[Optional[str]] = []  # memo: [] = not probed yet


def _local_slice_id() -> Optional[str]:
    """The pod slice THIS process's node advertises (None when the node
    carries no topology). One controller round-trip, memoized for the
    process lifetime — slice membership doesn't change under a live
    process. Routers use it to prefer ICI-local replicas."""
    if not _local_slice_cache:
        slice_id = None
        try:
            from ray_tpu.core.runtime import get_core_worker

            from ray_tpu.core.config import config as rt_config

            core = get_core_worker()
            me = core.node_id.hex()
            for n in core.controller.call(
                    "list_nodes", timeout=rt_config.ctrl_call_timeout_s):
                if n["node_id"] == me and n.get("slice"):
                    slice_id = n["slice"]["slice_id"]
                    break
        except Exception:
            slice_id = None
        _local_slice_cache.append(slice_id)
    return _local_slice_cache[0]


class _Router:
    """Per-process router for one deployment: pubsub-fed replica snapshot +
    client-side pow-2 routing with model and prefix-cache affinity."""

    _instances: Dict[str, "_Router"] = {}
    _instances_lock = threading.Lock()

    @classmethod
    def get(cls, name: str) -> "_Router":
        with cls._instances_lock:
            router = cls._instances.get(name)
            if router is None:
                router = cls(name)
                cls._instances[name] = router
            return router

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._replicas: List[Dict[str, Any]] = []  # {handle, id, models}
        self._inflight: Dict[str, int] = {}
        self._version = 0
        # Highest controller epoch whose snapshot this router applied:
        # snapshots from an OLDER epoch (a zombie controller racing its
        # replacement) are ignored — client-side belt to the pubsub
        # hub's server-side fencing suspender.
        self._ctrl_epoch = 0
        self._have_snapshot = threading.Event()
        self._max_ongoing = 8
        self._deleted = False
        # Disaggregated posture from the controller snapshot: routers of
        # a role="prefill" deployment splice __call__ requests across
        # the prefill and decode fleets; everything else routes legacy.
        self._role = "colocated"
        self._decode_dep: Optional[str] = None
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=64,
                                        thread_name_prefix="serve-router")
        self._watcher = threading.Thread(target=self._watch_loop,
                                         name=f"serve-watch-{name}",
                                         daemon=True)
        self._watcher.start()

    # -------------------------------------------------------- snapshots

    def _apply(self, version: int, snapshot: Dict[str, Any]) -> None:
        with self._lock:
            epoch = int(snapshot.get("epoch") or 0)
            if epoch and epoch < self._ctrl_epoch:
                # Zombie-epoch snapshot: keep serving the newer view.
                # (The version clock still advances with the poll loop,
                # so the next legitimate publish wakes us normally.)
                self._version = max(self._version, version)
                return
            if epoch:
                self._ctrl_epoch = epoch
            self._version = version
            self._deleted = snapshot.get("deleted", False)
            self._max_ongoing = snapshot.get("max_ongoing_requests", 8)
            self._role = snapshot.get("role") or "colocated"
            self._decode_dep = snapshot.get("decode_deployment")
            self._replicas = [
                {"handle": ActorHandle(ActorID(r["actor_id"])),
                 "id": r["replica_id"],
                 "models": set(r.get("models", [])),
                 "prefixes": set(r.get("prefixes", [])),
                 "slice_id": r.get("slice_id")}
                for r in snapshot.get("replicas", [])]
            live = {r["id"] for r in self._replicas}
            self._inflight = {k: v for k, v in self._inflight.items()
                              if k in live}
            ready = bool(self._replicas) or self._deleted
        if ready:
            self._have_snapshot.set()

    def _watch_loop(self) -> None:
        from ray_tpu.core.runtime import get_core_worker

        while not self._stop.is_set():
            try:
                core = get_core_worker()
                # Single-writer field: _version is only assigned by
                # _apply, and only THIS thread calls _apply — the
                # unlocked read can never observe a torn/foreign write.
                update = core.controller.call(
                    "psub_poll", SNAPSHOT_CHANNEL, self.name,
                    # graftlint: disable=unguarded-field-access
                    self._version, 10.0, timeout=25.0)
            except Exception:
                if self._stop.wait(0.5):
                    return
                continue
            if update is not None:
                self._apply(*update)

    def _known_to_controller(self) -> bool:
        """One cheap existence probe so unknown names fail fast (404), not
        after a 60s wait."""
        from ray_tpu.core.config import config as rt_config
        from ray_tpu.core.runtime import get_core_worker

        try:
            snap = get_core_worker().controller.call(
                "psub_snapshot", SNAPSHOT_CHANNEL,
                timeout=rt_config.ctrl_call_timeout_s)
            return self.name in snap
        except Exception:
            return True  # can't tell: fall through to the normal wait

    def _evict(self) -> None:
        with _Router._instances_lock:
            if _Router._instances.get(self.name) is self:
                del _Router._instances[self.name]
        self.stop()

    def wait_ready(self, timeout: float = 60.0) -> None:
        if not self._have_snapshot.is_set() and not self._known_to_controller():
            self._evict()
            raise KeyError(f"no deployment {self.name!r}")
        if not self._have_snapshot.wait(timeout):
            # Unknown deployment (or controller gone): evict this router so
            # a probe of a bad name doesn't leak a watcher + pool forever.
            self._evict()
            raise KeyError(
                f"no routing snapshot for deployment {self.name!r} "
                f"(does it exist?)")

    def wait_version(self, version: int, timeout: float = 60.0) -> None:
        """Block until this router has applied snapshot >= version (used by
        serve.run so a redeploy's first request can't route on a stale —
        possibly deleted — snapshot)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                if self._version >= version:
                    return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"router for {self.name!r} never saw snapshot v{version}")
            time.sleep(0.02)

    # ---------------------------------------------------------- routing

    def _pick(self, model_id: str, prefix_hashes=None):
        """Pow-2 choices on local in-flight counts; with a model id,
        replicas that already hold the model win (multiplex affinity);
        with prefix hashes, replicas advertising the request's leading
        token bucket win (prefix-cache affinity) — a hot system prompt
        stays resident on ONE replica's prefix pool instead of being
        re-prefilled on every replica."""
        from ray_tpu.core.config import config as rt_config

        # Resolved BEFORE taking the router lock: the first call is a
        # controller round-trip (memoized after), and an RPC under this
        # lock would head-of-line-block every concurrent pick (the
        # dial-under-lock class graftlint polices).
        here = (_local_slice_id() if rt_config.slice_affinity_enabled
                else None)
        with self._lock:
            replicas = self._replicas
            if not replicas:
                return None
            pool = replicas
            if model_id:
                warm = [r for r in replicas if model_id in r["models"]]
                # Warm replicas win unless saturated (then let a cold one
                # load the model rather than queueing behind the hot set).
                warm = [r for r in warm
                        if self._inflight.get(r["id"], 0) < self._max_ongoing]
                if warm:
                    pool = warm
            if prefix_hashes:
                # Longest advertised bucket wins; same saturation escape
                # valve as model affinity (least-loaded beats affinity
                # once the warm replica is at max_ongoing).
                for h in prefix_hashes:
                    warm = [r for r in pool if h in r["prefixes"]
                            and self._inflight.get(r["id"], 0)
                            < self._max_ongoing]
                    if warm:
                        pool = warm
                        break
            # ICI locality, weakest preference (model residency and a
            # prefix hit both save real compute; same-slice only saves
            # network): among the remaining candidates, stay on the
            # caller's own pod slice when an unsaturated replica lives
            # there — controller snapshots carry each replica's slice.
            if here is not None:
                near = [r for r in pool if r.get("slice_id") == here
                        and self._inflight.get(r["id"], 0)
                        < self._max_ongoing]
                if near:
                    pool = near
            if len(pool) == 1:
                chosen = pool[0]
            else:
                a, b = random.sample(range(len(pool)), 2)
                ca = self._inflight.get(pool[a]["id"], 0)
                cb = self._inflight.get(pool[b]["id"], 0)
                chosen = pool[a if ca <= cb else b]
            self._inflight[chosen["id"]] = (
                self._inflight.get(chosen["id"], 0) + 1)
            return chosen

    def _release(self, replica) -> None:
        with self._lock:
            rid = replica["id"]
            if rid in self._inflight:
                self._inflight[rid] = max(0, self._inflight[rid] - 1)

    def submit(self, method: str, args: tuple, kwargs: dict,
               model_id: str = "", timeout_s: Optional[float] = None
               ) -> Future:
        from ray_tpu.core.config import config as rt_config
        from ray_tpu.util import tracing

        fut: Future = Future()
        # The caller's span context is captured HERE: contextvars don't
        # follow work onto pool threads, and the request's whole trace
        # (router span -> attempt spans -> replica -> engine) must hang
        # under the span that submitted it (e.g. the proxy's http span).
        ctx = tracing.current() if rt_config.serve_trace_spans else None
        self._pool.submit(self._run_one, fut, method, args, kwargs,
                          model_id, timeout_s, ctx)
        return fut

    @staticmethod
    def _backoff_s(attempt: int) -> float:
        """Exponential backoff with +/-50% jitter: base * 2^attempt,
        decorrelated so N handles retrying the same replica death don't
        synchronize into a retry storm against the survivors."""
        from ray_tpu.core.config import config as rt_config

        base = rt_config.handle_retry_backoff_ms / 1e3
        return base * (2 ** attempt) * (0.5 + random.random())

    def _run_one(self, fut: Future, method, args, kwargs, model_id,
                 timeout_s: Optional[float] = None,
                 trace_ctx: Optional[tuple] = None) -> None:
        from contextlib import nullcontext

        from ray_tpu.core.config import config as rt_config
        from ray_tpu.util import tracing

        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        spans = rt_config.serve_trace_spans
        try:
            # One router span per request; each attempt gets a child
            # span tagged with the attempt ordinal and replica, and the
            # actor call made INSIDE it ships that span's context — so a
            # retried request's replica-side work stays parented under
            # the same request across attempts.
            with tracing.resume(trace_ctx), \
                    (tracing.trace(f"router:{self.name}", method=method)
                     if spans else nullcontext()):
                self.wait_ready()
                if self._splice_eligible(method, args):
                    fut.set_result(self._run_spliced(
                        args[0], model_id, deadline))
                else:
                    fut.set_result(self._call_with_retries(
                        method, args, kwargs, model_id, deadline,
                        _affinity_hashes(args)))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    def _call_with_retries(self, method, args, kwargs, model_id,
                           deadline: Optional[float],
                           prefix_hashes=None) -> Any:
        """One routed unary call: pick -> call -> return, retrying a
        dead replica within the handle budget (backoff, never past the
        absolute monotonic ``deadline``)."""
        from contextlib import nullcontext

        from ray_tpu.core.config import config as rt_config
        from ray_tpu.util import tracing

        budget = max(1, rt_config.handle_retry_budget)
        spans = rt_config.serve_trace_spans
        last_err: Optional[BaseException] = None
        for attempt in range(budget):
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                raise DeadlineExceededError(
                    f"deadline expired before attempt "
                    f"{attempt + 1} to {self.name!r}") from last_err
            replica = self._pick(model_id, prefix_hashes)
            if replica is None:
                # Advisory read: worst case a request that raced
                # the delete gets the "no replicas" message
                # instead of "was deleted" — both terminate it
                # identically.
                # graftlint: disable=unguarded-field-access
                if self._deleted:
                    raise RuntimeError(
                        f"deployment {self.name!r} was deleted")
                raise RuntimeError(
                    f"deployment {self.name!r} has no replicas")
            try:
                # The deadline ships as a RELATIVE duration; the
                # replica re-anchors it to its own clock. get()'s
                # grace past it only covers transit — the replica
                # enforces the deadline itself.
                with (tracing.trace("attempt", attempt=attempt,
                                    replica=replica["id"])
                      if spans else nullcontext()):
                    ref = replica["handle"].handle_request.remote(
                        method, args, kwargs, model_id, remaining)
                    return ray_tpu.get(
                        ref, timeout=(None if remaining is None
                                      else remaining + 10.0))
            except GetTimeoutError as e:
                raise DeadlineExceededError(
                    f"no reply from {self.name!r} within the "
                    f"request deadline") from e
            except (ActorDiedError, ActorUnavailableError) as e:
                # Replica died: forget it locally; the
                # controller's next snapshot heals the set.
                # Retry elsewhere — within the per-request
                # budget, with backoff, and never past the
                # deadline.
                last_err = e
                with self._lock:
                    self._replicas = [r for r in self._replicas
                                      if r["id"] != replica["id"]]
                if attempt + 1 >= budget:
                    break
                pause = self._backoff_s(attempt)
                if (deadline is not None
                        and time.monotonic() + pause >= deadline):
                    break  # the retry could not finish in time
                self._count_retry()
                time.sleep(pause)
            finally:
                self._release(replica)
        raise last_err

    # --------------------------------------- disaggregated splice

    def _splice_eligible(self, method: str, args: tuple,
                         stream: bool = False) -> bool:
        """Should this request split across the prefill/decode fleets?
        Only a role="prefill" deployment splices, only for generation-
        shaped requests, and only while the decode fleet has routable
        replicas — otherwise fall through to the legacy colocated path
        (prefill replicas run the full engine; role is routing posture,
        not capability)."""
        # a stale posture routes one request the legacy way, harmlessly
        if self._role != "prefill" or not self._decode_dep:
            return False
        if method not in (("__call__", "stream") if stream
                          else ("__call__",)):
            return False
        req = args[0] if args else None
        if not isinstance(req, dict) or req.get("tokens") is None:
            return False
        if not stream and req.get("stream"):
            return False  # generator path: _Router.stream splices it
        decode = _Router.get(self._decode_dep)
        if not decode._have_snapshot.is_set():
            return False  # decode fleet not routable yet: don't publish
        with decode._lock:
            return bool(decode._replicas)

    def _notify_handoff(self, replica, verb: str, desc) -> None:
        """Fire-and-forget lease notify back to the prefill replica
        (adopt-ack or abort). Best-effort by design: an unreachable
        prefill replica is a dead one, whose refs died with it, and the
        ledger's TTL sweep backstops a lost notify."""
        try:
            replica["handle"].handle_request.remote(
                verb, (desc["handoff_id"],), {}, "", None)
        except Exception:
            log_every("router.handoff_notify", 10.0, logger,
                      "handoff lease notify failed", exc_info=True)

    def _run_spliced(self, request, model_id,
                     deadline: Optional[float]) -> Any:
        """Disaggregated splice, unary: prefill on this fleet publishes
        the prompt's KV pages (``prefill_handoff``), the decode fleet
        adopts them (``decode_adopted``). The published lease is
        discharged on EVERY path: adopt-ack on success, abort on any
        decode-side failure; a prefill replica that dies mid-handoff
        needs nothing (its refs died with the owner process) and the
        request re-prefills within the retry budget."""
        from ray_tpu.core.config import config as rt_config
        from ray_tpu.core.errors import (HandoffAdoptError,
                                         RequestCancelledError)

        decode = _Router.get(self._decode_dep)
        prefix_hashes = _affinity_hashes((request,))
        budget = max(1, rt_config.handle_retry_budget)
        last_err: Optional[BaseException] = None
        for attempt in range(budget):
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                raise DeadlineExceededError(
                    f"deadline expired before splice attempt "
                    f"{attempt + 1} via {self.name!r}") from last_err
            replica = self._pick(model_id, prefix_hashes)
            if replica is None:
                raise RuntimeError(
                    f"deployment {self.name!r} has no replicas")
            try:
                ref = replica["handle"].handle_request.remote(
                    "prefill_handoff", (request,), {}, model_id,
                    remaining)
                desc = ray_tpu.get(
                    ref, timeout=(None if remaining is None
                                  else remaining + 10.0))
            except GetTimeoutError as e:
                raise DeadlineExceededError(
                    f"no prefill handoff from {self.name!r} within "
                    f"the request deadline") from e
            except (ActorDiedError, ActorUnavailableError) as e:
                # Prefill replica death mid-handoff: its object-plane
                # refs died with the owner process, so nothing strands
                # — forget it and re-prefill elsewhere.
                last_err = e
                with self._lock:
                    self._replicas = [r for r in self._replicas
                                      if r["id"] != replica["id"]]
                if attempt + 1 >= budget:
                    break
                pause = self._backoff_s(attempt)
                if (deadline is not None
                        and time.monotonic() + pause >= deadline):
                    break
                self._count_retry()
                time.sleep(pause)
                continue
            finally:
                self._release(replica)
            # Published: the lease is this router's to discharge. The
            # decode router retries a dead decode replica internally —
            # the descriptor stays valid (the prefill replica holds the
            # refs until we notify).
            try:
                result = decode._call_with_retries(
                    "decode_adopted", (request, desc), {}, model_id,
                    deadline, prefix_hashes)
            except BaseException as e:
                self._notify_handoff(replica, "abort_handoff", desc)
                for cause in _error_chain(e):
                    if isinstance(cause, (DeadlineExceededError,
                                          RequestCancelledError)):
                        raise  # terminal by contract: never fall back
                    if isinstance(cause, HandoffAdoptError):
                        # The decode fleet cannot splice these pages
                        # (geometry mismatch / payload gone with a dead
                        # owner): serve the request colocated, once.
                        logger.warning(
                            "handoff adopt failed (%s); falling back "
                            "to colocated on %r", cause, self.name)
                        return self._call_with_retries(
                            "__call__", (request,), {}, model_id,
                            deadline, prefix_hashes)
                raise
            self._notify_handoff(replica, "discharge_handoff", desc)
            return result
        raise last_err

    def _count_retry(self) -> None:
        from ray_tpu.core.config import config as rt_config

        if rt_config.serve_metrics_enabled:
            from ray_tpu.serve import metrics as smetrics

            smetrics.RETRIES.inc(1.0, {"deployment": self.name})

    def stream(self, method: str, args: tuple, kwargs: dict,
               model_id: str = "", chunk_items: int = 16,
               timeout_s: Optional[float] = None):
        """Generator of streamed items from one replica (or, for a
        role="prefill" deployment, spliced across the prefill and
        decode fleets): see ``_stream_plain`` / ``_stream_spliced``."""
        self.wait_ready()
        if self._splice_eligible(method, args, stream=True):
            yield from self._stream_spliced(
                method, args[0], model_id, chunk_items,
                (time.monotonic() + timeout_s
                 if timeout_s is not None else None))
            return
        yield from self._stream_plain(method, args, kwargs, model_id,
                                      chunk_items, timeout_s)

    def _stream_spliced(self, method, request, model_id,
                        chunk_items: int, deadline: Optional[float]):
        """Disaggregated splice, streaming: publish the prefill handoff
        here, then delegate to the decode router's stream (which adopts
        EAGERLY inside start_stream, so pre-first-item failures are
        visible before any token reaches the client). The lease is
        discharged at the first streamed item (adoption observably
        complete) and aborted on any pre-first-item failure."""
        from ray_tpu.core.config import config as rt_config
        from ray_tpu.core.errors import (HandoffAdoptError,
                                         RequestCancelledError)

        decode = _Router.get(self._decode_dep)
        prefix_hashes = _affinity_hashes((request,))
        budget = max(1, rt_config.handle_retry_budget)
        last_err: Optional[BaseException] = None
        for attempt in range(budget):
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                raise DeadlineExceededError(
                    f"deadline expired before the spliced stream via "
                    f"{self.name!r} started") from last_err
            replica = self._pick(model_id, prefix_hashes)
            if replica is None:
                raise RuntimeError(
                    f"deployment {self.name!r} has no replicas")
            try:
                desc = ray_tpu.get(
                    replica["handle"].handle_request.remote(
                        "prefill_handoff", (request,), {}, model_id,
                        remaining),
                    timeout=(None if remaining is None
                             else remaining + 10.0))
            except GetTimeoutError as e:
                raise DeadlineExceededError(
                    f"no prefill handoff from {self.name!r} within "
                    f"the request deadline") from e
            except (ActorDiedError, ActorUnavailableError) as e:
                last_err = e
                with self._lock:
                    self._replicas = [r for r in self._replicas
                                      if r["id"] != replica["id"]]
                if attempt + 1 >= budget:
                    break
                pause = self._backoff_s(attempt)
                if (deadline is not None
                        and time.monotonic() + pause >= deadline):
                    break
                self._count_retry()
                time.sleep(pause)
                continue
            finally:
                self._release(replica)
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            inner = decode.stream(
                "stream_adopted", (request, desc), {}, model_id,
                chunk_items=chunk_items, timeout_s=remaining)
            discharged = False
            try:
                for item in inner:
                    if not discharged:
                        discharged = True
                        self._notify_handoff(replica,
                                             "discharge_handoff", desc)
                    yield item
                if not discharged:  # empty stream still adopted
                    discharged = True
                    self._notify_handoff(replica,
                                         "discharge_handoff", desc)
                return
            except BaseException as e:
                if not discharged:
                    self._notify_handoff(replica, "abort_handoff", desc)
                for cause in _error_chain(e):
                    if isinstance(cause, (DeadlineExceededError,
                                          RequestCancelledError)):
                        raise
                    if isinstance(cause, HandoffAdoptError):
                        if discharged:
                            raise  # mid-stream: never replay tokens
                        logger.warning(
                            "handoff adopt failed (%s); falling back "
                            "to colocated stream on %r", cause,
                            self.name)
                        yield from self._stream_plain(
                            method, (request,), {}, model_id,
                            chunk_items,
                            (None if deadline is None
                             else deadline - time.monotonic()))
                        return
                raise
            finally:
                inner.close()
        raise last_err

    def _stream_plain(self, method: str, args: tuple, kwargs: dict,
                      model_id: str = "", chunk_items: int = 16,
                      timeout_s: Optional[float] = None):
        """Generator of streamed items from one replica: the replica's
        generator suspends between pulls (consumer-paced). The replica's
        in-flight slot and this router's count are held for the stream's
        lifetime (autoscaling sees streams as load).

        Replica death is retried (budget + backoff) only BEFORE the
        first item: once any token has been yielded the stream has
        observable state on the client, so a mid-stream retry would
        replay or corrupt it — the error propagates instead."""
        from contextlib import nullcontext

        from ray_tpu.core.config import config as rt_config
        from ray_tpu.util import tracing

        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        budget = max(1, rt_config.handle_retry_budget)
        spans = rt_config.serve_trace_spans
        self.wait_ready()
        prefix_hashes = _affinity_hashes(args)
        last_err: Optional[BaseException] = None
        for attempt in range(budget):
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                raise DeadlineExceededError(
                    f"deadline expired before the stream to "
                    f"{self.name!r} started") from last_err
            replica = self._pick(model_id, prefix_hashes)
            if replica is None:
                raise RuntimeError(
                    f"deployment {self.name!r} has no replicas")
            handle = replica["handle"]
            sid = None
            try:
                try:
                    # The attempt span wraps only start_stream: the
                    # engine captures its trace context at submit (which
                    # runs inside this actor call), so a pre-first-item
                    # retry re-parents the replica-side work under the
                    # new attempt while the stream stays one trace.
                    with (tracing.trace("stream-attempt", attempt=attempt,
                                        replica=replica["id"])
                          if spans else nullcontext()):
                        sid = ray_tpu.get(handle.start_stream.remote(
                            method, args, kwargs, model_id, remaining),
                            timeout=70.0)
                except (ActorDiedError, ActorUnavailableError) as e:
                    last_err = e
                    with self._lock:
                        self._replicas = [r for r in self._replicas
                                          if r["id"] != replica["id"]]
                    if attempt + 1 >= budget:
                        raise
                    pause = self._backoff_s(attempt)
                    if (deadline is not None
                            and time.monotonic() + pause >= deadline):
                        raise
                    self._count_retry()
                    time.sleep(pause)
                    continue
                while True:
                    items, done = ray_tpu.get(handle.next_chunks.remote(
                        sid, chunk_items), timeout=70.0)
                    yield from items
                    if done:
                        sid = None
                        return
            finally:
                if sid is not None:  # consumer bailed early: free the
                    try:             # slot + cancel the engine request
                        handle.cancel_stream.remote(sid)
                    except Exception:
                        # Cancel undeliverable: the replica frees the
                        # slot at its deadline instead — slower, and a
                        # systematic failure here is a capacity leak.
                        log_every("router.cancel_stream", 10.0, logger,
                                  "stream cancel to replica failed",
                                  exc_info=True)
                self._release(replica)

    def stop(self) -> None:
        self._stop.set()
        self._pool.shutdown(wait=False)

    @classmethod
    def reset_all(cls) -> None:
        with cls._instances_lock:
            routers, cls._instances = dict(cls._instances), {}
        for router in routers.values():
            router.stop()


class DeploymentHandle:
    """Serializable handle: any process holding it (or just the deployment
    name) can route requests (reference: ``serve/handle.py:714``)."""

    def __init__(self, name: str, method: str = "__call__",
                 multiplexed_model_id: str = "",
                 timeout_s: Optional[float] = None):
        self._name = name
        self._method = method
        self._model_id = multiplexed_model_id
        self._timeout_s = timeout_s

    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                timeout_s: Optional[float] = None
                ) -> "DeploymentHandle":
        """Per-request options; ``timeout_s`` sets the end-to-end
        deadline propagated with every request made through the returned
        handle (router retries stop at it, the replica re-anchors it,
        and a DecodeEngine finishes the slot at it)."""
        return DeploymentHandle(
            self._name,
            method_name if method_name is not None else self._method,
            (multiplexed_model_id if multiplexed_model_id is not None
             else self._model_id),
            timeout_s if timeout_s is not None else self._timeout_s)

    def remote(self, *args, **kwargs) -> Future:
        return _Router.get(self._name).submit(
            self._method, args, kwargs, self._model_id,
            timeout_s=self._timeout_s)

    def stream(self, *args, **kwargs):
        """Iterate a generator-returning deployment method incrementally
        (reference: handle streaming / chunked HTTP responses)."""
        return _Router.get(self._name).stream(
            self._method, args, kwargs, self._model_id,
            timeout_s=self._timeout_s)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._name, name, self._model_id,
                                self._timeout_s)

    def __reduce__(self):
        return (DeploymentHandle, (self._name, self._method, self._model_id,
                                   self._timeout_s))

    def __repr__(self):
        return f"DeploymentHandle({self._name!r}, {self._method!r})"
