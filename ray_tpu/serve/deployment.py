"""Deployments, replicas, routing, autoscaling — the Serve stack.

Analogue of the reference's Serve architecture (SURVEY §3.5): control plane
(``ServeController`` reconciling ``DeploymentState``,
``serve/_private/controller.py:86`` + ``deployment_state.py``) and data plane
(``DeploymentHandle`` -> ``Router.assign_request`` ->
power-of-two-choices replica picking, ``replica_scheduler/pow_2_scheduler.py
:49`` -> ``ReplicaActor.handle_request``, ``replica.py:231``), condensed:
the controller runs in the driver process with a reconcile thread; replicas
are actors; routing state (in-flight counts) lives client-side in the
handle, which is what the reference's pow-2 scheduler samples anyway.

Request autoscaling mirrors ``autoscaling_policy.py:12``: desired replicas =
ceil(total in-flight / target_ongoing_requests), clamped to [min, max],
applied by the reconcile loop.
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 5.0


class Deployment:
    def __init__(self, cls, name: Optional[str] = None,
                 num_replicas: int = 1,
                 ray_actor_options: Optional[Dict] = None,
                 autoscaling_config: Optional[AutoscalingConfig] = None,
                 max_ongoing_requests: int = 8):
        self.cls = cls
        self.name = name or cls.__name__
        self.num_replicas = num_replicas
        self.actor_options = ray_actor_options or {}
        self.autoscaling = autoscaling_config
        self.max_ongoing_requests = max_ongoing_requests
        self._init_args: tuple = ()
        self._init_kwargs: dict = {}

    def options(self, **overrides) -> "Deployment":
        dep = Deployment(self.cls, self.name, self.num_replicas,
                         dict(self.actor_options), self.autoscaling,
                         self.max_ongoing_requests)
        for k, v in overrides.items():
            setattr(dep, k if k != "name" else "name", v)
        return dep

    def bind(self, *args, **kwargs) -> "Deployment":
        self._init_args = args
        self._init_kwargs = kwargs
        return self


def deployment(_cls=None, **kwargs):
    """``@serve.deployment`` decorator (reference: ``serve/api.py``)."""

    def wrap(cls):
        return Deployment(cls, **kwargs)

    if _cls is not None:
        return wrap(_cls)
    return wrap


class _ReplicaWrapper:
    """Actor body hosting the user callable (reference: ReplicaActor +
    UserCallableWrapper, ``replica.py:231,750``)."""

    def __init__(self, cls_blob: bytes, args: tuple, kwargs: dict):
        from ray_tpu.core import serialization

        cls = serialization.loads_function(cls_blob)
        self._instance = cls(*args, **kwargs)

    def handle_request(self, method: str, args: tuple, kwargs: dict):
        target = (self._instance if method == "__call__"
                  else getattr(self._instance, method))
        if method == "__call__":
            return target(*args, **kwargs)
        return target(*args, **kwargs)

    def ping(self):
        return "pong"


class DeploymentHandle:
    """Client-side router with power-of-two-choices replica selection."""

    def __init__(self, state: "_DeploymentState", method: str = "__call__"):
        self._state = state
        self._method = method

    def options(self, method_name: str) -> "DeploymentHandle":
        return DeploymentHandle(self._state, method_name)

    def remote(self, *args, **kwargs):
        """Async: returns an ObjectRef-like future."""
        return self._state.submit(self._method, args, kwargs)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._state, name)


class _DeploymentState:
    """Controller-side record + data-plane routing for one deployment."""

    def __init__(self, deployment: Deployment):
        from ray_tpu.core import serialization

        self.deployment = deployment
        self.cls_blob = serialization.dumps_function(deployment.cls)
        self.replicas: List[Any] = []
        self.inflight: Dict[int, int] = {}  # id(replica actor) -> count
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=64,
                                        thread_name_prefix="serve-router")
        self._last_scale = time.monotonic()
        target = (deployment.autoscaling.min_replicas
                  if deployment.autoscaling else deployment.num_replicas)
        for _ in range(target):
            self._add_replica()

    def _add_replica(self) -> None:
        actor_cls = ray_tpu.remote(_ReplicaWrapper)
        opts = dict(self.deployment.actor_options)
        opts.setdefault("max_concurrency",
                        self.deployment.max_ongoing_requests)
        actor = actor_cls.options(**opts).remote(
            self.cls_blob, self.deployment._init_args,
            self.deployment._init_kwargs)
        with self._lock:
            self.replicas.append(actor)
            self.inflight[id(actor)] = 0

    def _remove_replica(self) -> None:
        with self._lock:
            if len(self.replicas) <= 1:
                return
            actor = self.replicas.pop()
            self.inflight.pop(id(actor), None)
        try:
            ray_tpu.kill(actor)
        except Exception:
            pass

    # ------------------------------------------------------------ routing

    def _acquire_replica(self):
        """Power-of-two-choices on client-side in-flight counts
        (pow_2_scheduler.py:49). Pick + increment happen under one lock
        acquisition, and inflight is keyed by replica identity, so a
        concurrent scale-down can't shift indices underneath a request."""
        with self._lock:
            n = len(self.replicas)
            if n == 1:
                actor = self.replicas[0]
            else:
                a, b = random.sample(range(n), 2)
                ca = self.inflight.get(id(self.replicas[a]), 0)
                cb = self.inflight.get(id(self.replicas[b]), 0)
                actor = self.replicas[a if ca <= cb else b]
            self.inflight[id(actor)] = self.inflight.get(id(actor), 0) + 1
            return actor

    def _release_replica(self, actor) -> None:
        with self._lock:
            key = id(actor)
            if key in self.inflight:
                self.inflight[key] = max(0, self.inflight[key] - 1)

    def submit(self, method: str, args: tuple, kwargs: dict) -> Future:
        fut: Future = Future()

        def run():
            actor = self._acquire_replica()
            try:
                ref = actor.handle_request.remote(method, args, kwargs)
                fut.set_result(ray_tpu.get(ref))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
            finally:
                self._release_replica(actor)

        self._pool.submit(run)
        return fut

    # -------------------------------------------------------- autoscaling

    def reconcile(self) -> None:
        auto = self.deployment.autoscaling
        if auto is None:
            return
        with self._lock:
            total_inflight = sum(self.inflight.values())
            current = len(self.replicas)
        desired = max(auto.min_replicas,
                      min(auto.max_replicas,
                          -(-int(total_inflight) //
                            max(1, int(auto.target_ongoing_requests)))))
        now = time.monotonic()
        if desired > current and now - self._last_scale > auto.upscale_delay_s:
            self._add_replica()
            self._last_scale = now
        elif (desired < current
              and now - self._last_scale > auto.downscale_delay_s):
            self._remove_replica()
            self._last_scale = now

    def shutdown(self) -> None:
        with self._lock:
            replicas, self.replicas = list(self.replicas), []
        for actor in replicas:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        self._pool.shutdown(wait=False)

    def num_replicas(self) -> int:
        with self._lock:
            return len(self.replicas)
