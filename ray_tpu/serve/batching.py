"""Dynamic request batching with TPU-friendly size bucketing.

Analogue of the reference's ``serve/batching.py`` (``@serve.batch``): calls
accumulate until ``max_batch_size`` or ``batch_wait_timeout_s``, then one
batched invocation serves them all. TPU adaptation: ``pad_to_buckets`` pads
each batch up to the nearest bucket size so a jitted model sees only a few
static shapes (each new shape is an XLA recompile — the reference's
dynamic batch sizes are hostile to TPU serving, SURVEY §7 hard part 5).
"""

from __future__ import annotations

import functools
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Sequence


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, timeout_s: float,
                 buckets: Optional[Sequence[int]]):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.buckets = sorted(buckets) if buckets else None
        self._lock = threading.Lock()
        self._pending: List[tuple] = []  # (item, Future)
        self._timer: Optional[threading.Timer] = None

    def submit(self, instance, item) -> Future:
        fut: Future = Future()
        flush = False
        with self._lock:
            self._pending.append((item, fut))
            if len(self._pending) >= self.max_batch_size:
                flush = True
            elif self._timer is None:
                self._timer = threading.Timer(
                    self.timeout_s, self._flush, args=(instance,))
                self._timer.daemon = True
                self._timer.start()
        if flush:
            self._flush(instance)
        return fut

    def _flush(self, instance) -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            batch, self._pending = self._pending, []
        if not batch:
            return
        items = [b[0] for b in batch]
        futures = [b[1] for b in batch]
        n = len(items)
        padded = items
        if self.buckets:
            target = next((b for b in self.buckets if b >= n),
                          self.buckets[-1])
            while len(padded) < target:
                padded = padded + [items[-1]]
        try:
            if instance is not None:
                results = self.fn(instance, padded)
            else:
                results = self.fn(padded)
            for fut, result in zip(futures, results[:n]):
                fut.set_result(result)
        except BaseException as e:  # noqa: BLE001
            for fut in futures:
                fut.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01,
          pad_to_buckets: Optional[Sequence[int]] = None):
    """Decorator: the wrapped method receives a *list* of requests and must
    return a list of responses of the same length (padding excluded)."""

    def wrap(fn):
        # The queue holds locks/timers, so it must be created lazily inside
        # the replica process (the decorated class is pickled to replicas).
        attr = f"__batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(self, item):
            queue = getattr(self, attr, None)
            if queue is None:
                # dict.setdefault is atomic under the GIL: concurrent first
                # calls converge on one queue (no unpicklable lock captured).
                queue = self.__dict__.setdefault(
                    attr, _BatchQueue(fn, max_batch_size,
                                      batch_wait_timeout_s, pad_to_buckets))
            return queue.submit(self, item).result()

        wrapper.__ray_tpu_batched__ = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
