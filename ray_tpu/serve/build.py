"""Config-file serve deployment (reference: ``serve build`` /
``serve deploy config.yaml`` — ``serve/schema.py`` ServeDeploySchema +
``serve/scripts.py``).

A YAML/dict config declares applications by import path; ``deploy_config``
imports each app, applies per-deployment overrides, and runs it against
the (detached) serve controller — so deployments are declarative and
re-runnable from CI, not just from a driver script.

Schema (subset of the reference's, same shape)::

    applications:
      - name: summarizer
        import_path: my_module:app      # a Deployment (bound or not)
        route_prefix: /summarize        # optional
        num_replicas: 2                 # optional override
        max_ongoing_requests: 8         # optional override
        mesh_shape: [2, 4]              # optional: chips per replica
        init_args: []                   # optional (unbound deployments)
        init_kwargs: {}

CLI: ``python -m ray_tpu serve deploy config.yaml | status | shutdown``.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional, Union


def _load_import_path(spec: str):
    module_name, _, attr = spec.partition(":")
    if not attr:
        raise ValueError(
            f"import_path {spec!r} must be 'module:attribute'")
    module = importlib.import_module(module_name)
    obj = module
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def load_config(path_or_dict: Union[str, Dict[str, Any]]) -> Dict[str, Any]:
    if isinstance(path_or_dict, dict):
        return path_or_dict
    import yaml

    with open(path_or_dict) as f:
        return yaml.safe_load(f)


def deploy_config(path_or_dict: Union[str, Dict[str, Any]],
                  ready_timeout_s: float = 60.0) -> List[Any]:
    """Deploy every application in the config; returns their handles."""
    from ray_tpu import serve
    from ray_tpu.serve.deployment import Deployment

    config = load_config(path_or_dict)
    apps = config.get("applications") or []
    if not apps:
        raise ValueError("config has no 'applications'")
    handles = []
    for app_cfg in apps:
        target = _load_import_path(app_cfg["import_path"])
        if not isinstance(target, Deployment):
            raise TypeError(
                f"{app_cfg['import_path']} resolved to {type(target)}; "
                f"expected a @serve.deployment object")
        overrides = {k: app_cfg[k] for k in
                     ("num_replicas", "max_ongoing_requests",
                      "autoscaling_config", "mesh_shape")
                     if k in app_cfg}
        if isinstance(overrides.get("autoscaling_config"), dict):
            from ray_tpu.serve.deployment import AutoscalingConfig

            overrides["autoscaling_config"] = AutoscalingConfig(
                **overrides["autoscaling_config"])
        # options() always: it clones, so bind() below never mutates the
        # module-level Deployment (one import_path can serve many apps).
        target = target.options(**overrides)
        if app_cfg.get("init_args") or app_cfg.get("init_kwargs"):
            target = target.bind(*(app_cfg.get("init_args") or ()),
                                 **(app_cfg.get("init_kwargs") or {}))
        handles.append(serve.run(
            target,
            name=app_cfg.get("name"),
            route_prefix=app_cfg.get("route_prefix"),
            ready_timeout_s=ready_timeout_s))
    return handles
