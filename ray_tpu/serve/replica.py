"""ReplicaActor: hosts the user callable + multiplexed model cache.

Analogue of the reference's ``ReplicaActor`` + ``UserCallableWrapper``
(``serve/_private/replica.py:231,750``) and the replica half of model
multiplexing (``serve/multiplex.py`` — per-replica LRU of loaded models,
residency reported to the controller for model-aware routing).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

_current_model_id = threading.local()
_current_deadline = threading.local()

# Which deployment/replica THIS worker process hosts — set by
# ReplicaActor.__init__ before the user class is constructed, so a
# DecodeEngine built inside it labels its SLO metrics by deployment
# without the engine ever knowing the serve plane exists. A process
# hosts at most one replica (replicas are dedicated actors).
_replica_ident: Dict[str, str] = {"deployment": "", "replica_id": ""}


def replica_ident() -> Dict[str, str]:
    """{'deployment', 'replica_id'} of the replica hosted by this
    process (empty strings outside a replica worker)."""
    return dict(_replica_ident)


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id of the in-flight request (reference:
    ``serve.get_multiplexed_model_id``)."""
    return getattr(_current_model_id, "value", "")


def request_deadline_s() -> Optional[float]:
    """Inside a replica: seconds remaining on the in-flight request's
    deadline, or None when the caller set none. The deadline is
    propagated as a RELATIVE duration at every hop (proxy -> handle ->
    replica) so it never depends on cross-process clock agreement; here
    it is re-anchored to this process's monotonic clock on arrival."""
    deadline = getattr(_current_deadline, "value", None)
    if deadline is None:
        return None
    return deadline - time.monotonic()


class _MultiplexCache:
    """Per-replica LRU of loaded models (multiplex.py's model cache)."""

    def __init__(self, loader, capacity: int):
        self._loader = loader
        self._capacity = max(1, capacity)
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()

    def get(self, instance, model_id: str):
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
        model = self._loader(instance, model_id)
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            while len(self._models) > self._capacity:
                old_id, old = self._models.popitem(last=False)
                del old
        return model

    def loaded(self) -> List[str]:
        with self._lock:
            return list(self._models)


def multiplexed(max_num_models_per_replica: int = 3):
    """``@serve.multiplexed`` — wraps a ``get_model(self, model_id)`` loader
    with a per-replica LRU cache (reference: ``serve/multiplex.py``). The
    cache is created lazily on the instance (decoration-time state would
    make the user class unpicklable — it ships to replicas by value)."""

    def wrap(loader):
        attr = f"__mux_cache_{loader.__name__}"

        def cached(self, model_id: Optional[str] = None):
            cache = getattr(self, attr, None)
            if cache is None:
                cache = _MultiplexCache(loader, max_num_models_per_replica)
                setattr(self, attr, cache)
            if model_id is None:
                model_id = get_multiplexed_model_id()
            return cache.get(self, model_id)

        cached._is_multiplexed = True
        return cached

    return wrap


def loaded_model_ids(instance) -> List[str]:
    """All model ids resident in ``instance``'s multiplex caches."""
    out: List[str] = []
    for name, value in vars(instance).items():
        if name.startswith("__mux_cache_") and isinstance(
                value, _MultiplexCache):
            out.extend(value.loaded())
    return out


class ReplicaActor:
    def __init__(self, cls_blob: bytes, args: tuple, kwargs: dict,
                 replica_id: str = "", owner_epoch: int = 0,
                 role: str = ""):
        from ray_tpu.core import serialization

        # Disaggregated posture ("prefill" / "decode" / "" = colocated):
        # routing-plane metadata, reported back through stats() so
        # serve.status() shows each replica's role. The hosted class is
        # identical either way — role never changes engine behavior.
        self._role = role
        if replica_id:
            # Before the user class runs: its __init__ may build the
            # engine that reads this identity for metric labels.
            _replica_ident["replica_id"] = replica_id
            _replica_ident["deployment"] = replica_id.rsplit("#", 1)[0]
        cls = serialization.loads_function(cls_blob)
        self._instance = cls(*args, **kwargs)
        self._sub_slice: Optional[Dict[str, Any]] = None
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        self._started = time.monotonic()
        # The controller epoch that owns this replica: assigned at
        # spawn, re-pushed by a restarted controller when it ADOPTS the
        # replica (set_owner_epoch). Exported as the serve_replica_epoch
        # gauge so `ray_tpu doctor` can flag replicas no live controller
        # epoch owns (orphan-replica).
        self._owner_epoch = int(owner_epoch)
        if replica_id:
            from ray_tpu.util import metrics as um

            um.add_collector(self._collect_epoch)

    def _collect_epoch(self) -> None:
        from ray_tpu.serve import metrics as smetrics

        smetrics.REPLICA_EPOCH.set(
            float(self._owner_epoch),
            {"deployment": _replica_ident["deployment"]})

    def set_owner_epoch(self, epoch: int) -> None:
        """Adoption handshake from a restarted controller: monotonic —
        a zombie's stale push can't regress the owning epoch."""
        self._owner_epoch = max(self._owner_epoch, int(epoch))

    def handle_request(self, method: str, args: tuple, kwargs: dict,
                       multiplexed_model_id: str = "",
                       deadline_s: Optional[float] = None):
        with self._lock:
            self._ongoing += 1
            self._total += 1
        _current_model_id.value = multiplexed_model_id
        _current_deadline.value = (time.monotonic() + deadline_s
                                   if deadline_s is not None else None)
        try:
            target = (self._instance if method == "__call__"
                      else getattr(self._instance, method))
            return target(*args, **kwargs)
        finally:
            _current_model_id.value = ""
            _current_deadline.value = None
            with self._lock:
                self._ongoing -= 1

    # ------------------------------------------------- streaming sessions
    #
    # A generator-returning callable streams INCREMENTALLY: the consumer
    # pulls batches with next_chunks (actor calls), so the generator is
    # suspended between pulls and production is backpressured by the
    # consumer (reference: proxy.py's streaming responses over
    # ASGI receive/send; here the handle is the transport).

    def start_stream(self, method: str, args: tuple, kwargs: dict,
                     multiplexed_model_id: str = "",
                     deadline_s: Optional[float] = None) -> str:
        import uuid

        with self._lock:
            self._ongoing += 1
            self._total += 1
        _current_model_id.value = multiplexed_model_id
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        _current_deadline.value = deadline
        try:
            target = (self._instance if method == "__call__"
                      else getattr(self._instance, method))
            result = target(*args, **kwargs)
            iterator = iter(result)
        except BaseException:
            with self._lock:
                self._ongoing -= 1
            raise
        finally:
            _current_model_id.value = ""
            _current_deadline.value = None
        sid = uuid.uuid4().hex[:16]
        self._streams = getattr(self, "_streams", {})
        self._streams[sid] = (iterator, multiplexed_model_id, deadline)
        return sid

    def next_chunks(self, stream_id: str, max_items: int = 16,
                    deadline_s: float = 2.0):
        """Pull up to ``max_items``, returning EARLY with whatever arrived
        once ``deadline_s`` elapses — a slow-but-healthy producer must
        stream partial batches, not stall the consumer's RPC timeout until
        the full batch exists. Returns (items, done); the stream's ongoing
        slot frees when the iterator is exhausted."""
        entry = getattr(self, "_streams", {}).get(stream_id)
        if entry is None:
            raise KeyError(f"unknown stream {stream_id}")
        iterator, model_id, req_deadline = entry
        items = []
        done = False
        deadline = time.monotonic() + deadline_s
        _current_model_id.value = model_id  # generator body resumes here
        _current_deadline.value = req_deadline
        try:
            for _ in range(max_items):
                items.append(next(iterator))
                if time.monotonic() > deadline:
                    break
        except StopIteration:
            done = True
        except BaseException:
            self.cancel_stream(stream_id)
            raise
        finally:
            _current_model_id.value = ""
            _current_deadline.value = None
        if done:
            self.cancel_stream(stream_id)
        return items, done

    def cancel_stream(self, stream_id: str) -> None:
        entry = getattr(self, "_streams", {}).pop(stream_id, None)
        if entry is not None:
            try:
                entry[0].close()
            except Exception:
                from ray_tpu.util.ratelimit import log_every

                # close() runs the generator's cleanup (engine cancel,
                # slot free) — a failure here can strand engine state.
                log_every("replica.stream_close", 10.0, logger,
                          "closing stream generator failed",
                          exc_info=True)
            with self._lock:
                self._ongoing -= 1

    def set_topology(self, assignment: Dict[str, Any]) -> None:
        """Sub-slice assignment from the serve controller (which chips
        of which slice this replica spans). Stored here and forwarded to
        the user instance when it cares (e.g. LlamaDecodeDeployment
        reports it through replica_metrics; a real multi-host replica
        would select jax devices by the assignment's chip coords)."""
        self._sub_slice = dict(assignment)
        fwd = getattr(self._instance, "set_topology", None)
        if callable(fwd):
            fwd(assignment)

    def set_admission(self, queue_max: int) -> bool:
        """Admission-cap override from the serve controller (the
        autopilot shed-tenant action): forwarded to the user instance's
        ``set_admission`` when it implements one, else applied to a
        hosted ``engine``'s ``queue_max`` directly. Returns whether
        anything applied (a deployment with no bounded queue has
        nothing to shed)."""
        fwd = getattr(self._instance, "set_admission", None)
        if callable(fwd):
            fwd(int(queue_max))
            return True
        eng = getattr(self._instance, "engine", None)
        if eng is not None and hasattr(eng, "queue_max"):
            eng.queue_max = max(1, int(queue_max))
            return True
        return False

    def engine_timeline(self) -> Dict[str, Any]:
        """The hosted instance's step-timeline dump (empty for non-engine
        deployments): phase rows + page/compile events, merged by
        ``ray_tpu timeline --serve`` into the cross-process trace."""
        fn = getattr(self._instance, "timeline", None)
        if callable(fn):
            try:
                return dict(fn())
            except Exception:
                from ray_tpu.util.ratelimit import log_every

                log_every("replica.timeline", 30.0, logger,
                          "instance timeline dump failed", exc_info=True)
        return {"rows": []}

    def stats(self) -> Dict[str, Any]:
        models = loaded_model_ids(self._instance)
        # Instance-reported metrics (e.g. a DecodeEngine's backlog as
        # "load" and its prefix-cache residency as "prefixes"): merged in
        # so the controller autoscales on decode backlog — a full decode
        # queue behind idle HTTP concurrency is NOT zero load — and the
        # router can steer shared prefixes to the replica holding them.
        out: Dict[str, Any] = {}
        metrics = getattr(self._instance, "replica_metrics", None)
        if callable(metrics):
            try:
                out = dict(metrics())
            except Exception:
                out = {}
        with self._lock:
            out.update({"ongoing": self._ongoing, "total": self._total,
                        "models": models,
                        "uptime_s": time.monotonic() - self._started})
        if self._role:
            out["role"] = self._role
        return out

    def ping(self) -> str:
        return "pong"
