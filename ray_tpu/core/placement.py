"""Placement groups: gang reservation of resource bundles across nodes.

Analogue of the reference's ``python/ray/util/placement_group.py`` API over
the GCS-side 2PC scheduler (``gcs_placement_group_scheduler.h``). Strategies
PACK / SPREAD / STRICT_PACK / STRICT_SPREAD match ``common.proto:937-944``.
On TPU, a placement group is the gang-scheduling primitive: a pod slice is
reserved as one STRICT_SPREAD group with a bundle per TPU-VM host (see
``ray_tpu.tpu.slice_placement_group``), generalizing the reference's
``TPU-{pod_type}-head`` resource hack (``_private/accelerators/tpu.py:381``)
into a scheduler-native mechanism.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.errors import RayTpuError
from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.runtime import get_core_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID,
                 bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy

    def ready(self, timeout: float = 30.0) -> bool:
        """Block until all bundles are reserved (reference: ``pg.ready()``)."""
        core = get_core_worker()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = core.controller.call("get_placement_group", self.id.binary())
            if info is not None and info["state"] == "CREATED":
                return True
            # Retry the 2PC reservation (capacity may have freed up).
            info = core.controller.call(
                "create_placement_group", self.id.binary(), self.bundles,
                self.strategy)
            if info.get("state") == "CREATED":
                return True
            time.sleep(0.2)
        return False

    def bundle_node(self, index: int):
        """Return (node_id_bytes, node_addr) hosting bundle ``index``."""
        core = get_core_worker()
        info = core.controller.call("get_placement_group", self.id.binary())
        if info is None or index not in info["placement"]:
            raise RayTpuError(f"bundle {index} of pg {self.id.hex()} not placed")
        return info["placement"][index]

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK") -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    core = get_core_worker()
    pg_id = PlacementGroupID.from_random()
    core.controller.call("create_placement_group", pg_id.binary(),
                         [dict(b) for b in bundles], strategy)
    return PlacementGroup(pg_id, [dict(b) for b in bundles], strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    core = get_core_worker()
    core.controller.call("remove_placement_group", pg.id.binary())


class PlacementGroupSchedulingStrategy:
    """Pin a task/actor to a bundle of a placement group (reference:
    ``util/scheduling_strategies.py``)."""

    def __init__(self, placement_group: PlacementGroup,
                 placement_group_bundle_index: int = 0):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft
