"""Placement groups: gang reservation of resource bundles across nodes.

Analogue of the reference's ``python/ray/util/placement_group.py`` API over
the GCS-side 2PC scheduler (``gcs_placement_group_scheduler.h``). Strategies
PACK / SPREAD / STRICT_PACK / STRICT_SPREAD match ``common.proto:937-944``.
On TPU, a placement group is the gang-scheduling primitive: a pod slice is
reserved as one STRICT_SPREAD group with a bundle per TPU-VM host (see
``ray_tpu.tpu.slice_placement_group``), generalizing the reference's
``TPU-{pod_type}-head`` resource hack (``_private/accelerators/tpu.py:381``)
into a scheduler-native mechanism.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.errors import RayTpuError
from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.rpc_stubs import ControllerStub
from ray_tpu.core.runtime import get_core_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID,
                 bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy

    def ready(self, timeout: float = 30.0) -> bool:
        """Block until all bundles are reserved (reference: ``pg.ready()``)."""
        core = get_core_worker()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            stub = ControllerStub(core.controller)
            info = stub.get_placement_group(self.id.binary())
            if info is not None and info["state"] == "CREATED":
                return True
            # Retry the 2PC reservation (capacity may have freed up).
            info = stub.create_placement_group(
                self.id.binary(), self.bundles, self.strategy)
            if info.get("state") == "CREATED":
                return True
            time.sleep(0.2)
        return False

    def bundle_node(self, index: int):
        """Return (node_id_bytes, node_addr) hosting bundle ``index``."""
        core = get_core_worker()
        info = ControllerStub(core.controller).get_placement_group(
            self.id.binary())
        if info is None or index not in info["placement"]:
            raise RayTpuError(f"bundle {index} of pg {self.id.hex()} not placed")
        return info["placement"][index]

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK") -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    core = get_core_worker()
    pg_id = PlacementGroupID.from_random()
    ControllerStub(core.controller).create_placement_group(
        pg_id.binary(), [dict(b) for b in bundles], strategy)
    return PlacementGroup(pg_id, [dict(b) for b in bundles], strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    core = get_core_worker()
    ControllerStub(core.controller).remove_placement_group(
        pg.id.binary())


# ------------------------------------------------ sub-slice reservations
#
# The mesh-parallel serving primitive (ROADMAP #1): a GSPMD replica does
# not want "n chips somewhere" — it wants an ICI-CONTIGUOUS rectangle of
# ONE slice's chip grid. The controller's TopologyView owns the grids
# (nodes advertise their slice at registration, core/topology.py); this
# is the client half.


class SubSliceReservation:
    """A held sub-slice: release it when the replica spanning it dies."""

    def __init__(self, assignment: Dict[str, Any]):
        self.assignment = dict(assignment)

    @property
    def reservation_id(self) -> str:
        return self.assignment["reservation_id"]

    @property
    def slice_id(self) -> str:
        return self.assignment["slice_id"]

    @property
    def chips(self) -> int:
        return int(self.assignment["chips"])

    @property
    def nodes(self) -> List[str]:
        return list(self.assignment.get("nodes", []))

    def release(self) -> bool:
        core = get_core_worker()
        return ControllerStub(core.controller).release_subslice(
            self.reservation_id)

    def __repr__(self):
        return (f"SubSliceReservation({self.reservation_id!r}, "
                f"slice={self.slice_id!r}, shape="
                f"{tuple(self.assignment['shape'])})")


def reserve_subslice(chips: int = 0,
                     shape: Optional[Any] = None,
                     owner: str = "") -> Optional[SubSliceReservation]:
    """Reserve a contiguous sub-slice (``shape`` = chip-grid rectangle,
    e.g. a replica's ``(batch, model)`` mesh footprint; bare ``chips``
    folds to the most-square block). Returns None when no single
    advertised slice can host it contiguously — the caller queues or
    rejects, it never gets a fragment straddling slices."""
    core = get_core_worker()
    sub = ControllerStub(core.controller).reserve_subslice(
        owner or f"driver-{os.getpid()}",
        int(chips), list(shape) if shape is not None else None)
    return SubSliceReservation(sub) if sub is not None else None


def cluster_topology() -> Dict[str, Any]:
    """Every advertised slice's grid, free chips, fragmentation, and
    live sub-slice reservations (controller ``topology_state`` RPC)."""
    core = get_core_worker()
    return ControllerStub(core.controller).topology_state()


class PlacementGroupSchedulingStrategy:
    """Pin a task/actor to a bundle of a placement group (reference:
    ``util/scheduling_strategies.py``)."""

    def __init__(self, placement_group: PlacementGroup,
                 placement_group_bundle_index: int = 0):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft
