"""Exception hierarchy for the runtime.

Analogue of the reference's ``python/ray/exceptions.py``: user-visible errors
raised by ``get``/``remote``/actor calls. Errors that occur inside a remote
task are captured, pickled, and re-raised at the caller wrapped in
``TaskError`` so the original traceback is preserved as text.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception.

    Stored in the object store in place of the task's return value; re-raised
    on ``get`` (reference: ``RayTaskError`` in ``python/ray/exceptions.py``).
    """

    def __init__(self, cause: BaseException, task_desc: str = "", tb: str = ""):
        self.cause = cause
        self.task_desc = task_desc
        if tb:
            self.tb = tb
        elif isinstance(cause, BaseException):
            self.tb = "".join(traceback.format_exception(
                type(cause), cause, cause.__traceback__))
        else:
            self.tb = str(cause)
        super().__init__(f"Task {task_desc} failed:\n{self.tb}")

    def __reduce__(self):
        # The cause itself may be unpicklable (or carry an unpicklable
        # traceback); ship a picklable surrogate plus the formatted text.
        cause = self.cause
        try:
            import pickle

            pickle.dumps(cause)
        except Exception:
            cause = RayTpuError(repr(self.cause))
        return (TaskError, (cause, self.task_desc, self.tb))


class WorkerCrashedError(RayTpuError):
    """The worker process executing a task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """An actor is dead; pending and future calls fail with this."""

    def __init__(self, actor_id=None, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id} is dead. {reason}")


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """An object could not be found or reconstructed."""


class ObjectFreedError(RayTpuError):
    """The object was explicitly freed."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get(ref, timeout=...)`` timed out."""


class RuntimeEnvSetupError(RayTpuError):
    """Setting up a runtime environment for a task/actor failed."""


class NodeDiedError(RayTpuError):
    """The node hosting a task/object died."""


class PendingCallsLimitExceeded(RayTpuError):
    """Actor max_pending_calls exceeded."""


class OutOfMemoryError(RayTpuError):
    """Object store is out of memory and eviction could not make room."""


# ------------------------------------------------- serve request lifecycle
#
# Typed terminal outcomes for a serve-plane request (reference: Ray Serve's
# BackPressureError / RequestCancelledError / deadline handling in
# serve/_private/proxy.py). These travel from the DecodeEngine / replica
# through actor-call error shipping to the handle and the HTTP proxy, which
# maps them onto status codes (503 + Retry-After, 504, 499).


class DeadlineExceededError(RayTpuError, TimeoutError):
    """The request's deadline passed before generation completed.

    Raised at admission (the deadline expired while queued) or mid-decode
    (the engine checks at every ``step()`` and frees the slot instead of
    burning decode steps for a caller that already gave up)."""


class RequestCancelledError(RayTpuError):
    """The request was cancelled (client disconnected / stream closed)
    before completing."""


class OverloadedError(RayTpuError):
    """The serving queue is at capacity; the request was shed at enqueue.

    Carries ``retry_after_s`` — the replica's estimate (from observed
    token throughput) of when a slot will free — which the HTTP proxy
    surfaces as a 503 ``Retry-After`` header."""

    def __init__(self, message: str = "server overloaded",
                 retry_after_s: float = 1.0):
        self.retry_after_s = float(retry_after_s)
        super().__init__(message)

    def __reduce__(self):
        return (OverloadedError, (self.args[0] if self.args else
                                  "server overloaded", self.retry_after_s))


class HandoffAdoptError(RayTpuError):
    """A decode replica could not adopt a published KV-page handoff
    (page-geometry mismatch, payload shape that does not fit the pool,
    or the page payload refs were already gone).

    Raised by ``DecodeEngine.submit(adopt=...)`` validation and shipped
    back through the actor-call error path; the router treats it as
    "this splice cannot work" and falls back to the colocated path
    after aborting the prefill side's lease."""
