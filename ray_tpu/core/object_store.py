"""Owner-side in-process object store.

Analogue of the reference's ``CoreWorkerMemoryStore``
(``src/ray/core_worker/store_provider/memory_store/memory_store.h:43``): every
process holds the values it owns (task returns, ``put`` objects) — or, for
values that landed in the node's shared-memory store, a locator — and serves
them to remote borrowers over its RPC server. Entries are created *pending*
at task-submission time and fulfilled when the task replies, so ``get`` is a
wait on an event, and remote processes can long-poll the owner.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.core.errors import ObjectFreedError, GetTimeoutError
from ray_tpu.core.ids import ObjectID


class _Entry:
    __slots__ = ("event", "data", "shm_ref", "shm_view", "shm_pin", "error",
                 "freed")

    def __init__(self):
        self.event = threading.Event()
        self.data: Optional[bytes] = None      # serialized frame (inline path)
        self.shm_ref = None                    # shm locator dict (shm path)
        self.shm_view = None                   # pinned local ShmView, if open
        self.shm_pin = None                    # owner's primary-copy pin
        self.error: Optional[BaseException] = None  # submission-level failure
        self.freed = False


class MemoryStore:
    def __init__(self):
        self._entries: Dict[ObjectID, _Entry] = {}
        self._lock = threading.Lock()

    def _entry(self, oid: ObjectID) -> _Entry:
        with self._lock:
            entry = self._entries.get(oid)
            if entry is None:
                entry = _Entry()
                self._entries[oid] = entry
            return entry

    def create_pending(self, oid: ObjectID) -> None:
        self._entry(oid)

    def put_serialized(self, oid: ObjectID, data: bytes) -> None:
        entry = self._entry(oid)
        entry.data = data
        entry.event.set()

    def put_shm(self, oid: ObjectID, shm_ref) -> None:
        entry = self._entry(oid)
        entry.shm_ref = shm_ref
        entry.event.set()

    def put_error(self, oid: ObjectID, error: BaseException) -> None:
        entry = self._entry(oid)
        entry.error = error
        entry.event.set()

    def is_ready(self, oid: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(oid)
        return entry is not None and entry.event.is_set()

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._entries

    def wait_ready(self, oid: ObjectID, timeout: Optional[float]) -> _Entry:
        entry = self._entry(oid)
        if not entry.event.wait(timeout):
            raise GetTimeoutError(
                f"Object {oid.hex()} not ready within {timeout}s")
        if entry.freed:
            raise ObjectFreedError(f"Object {oid.hex()} was freed")
        if entry.error is not None:
            raise entry.error
        return entry

    def put_shm_ref(self, oid: ObjectID, shm_ref: dict) -> None:
        entry = self._entry(oid)
        entry.shm_ref = shm_ref
        entry.event.set()

    def free(self, oid: ObjectID) -> None:
        with self._lock:
            entry = self._entries.get(oid)
            if entry is None:
                return
            entry.data = None
            entry.shm_ref = None
            if entry.shm_view is not None:
                entry.shm_view.release()
                entry.shm_view = None
            if entry.shm_pin is not None:
                entry.shm_pin.release()
                entry.shm_pin = None
            entry.freed = True
            entry.event.set()

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            self._entries.pop(oid, None)

    def size(self) -> int:
        with self._lock:
            return len(self._entries)


def wait_any(
    store: MemoryStore,
    oids,
    num_ready: int,
    timeout: Optional[float],
    poll=None,
):
    """Block until ``num_ready`` of ``oids`` are ready locally (or ``poll``
    reports them ready remotely). Returns (ready, not_ready) preserving order.
    Used by ``api.wait`` (reference: ``CoreWorker::Wait``, core_worker.h:804).
    """
    deadline = None if timeout is None else time.monotonic() + timeout
    interval = 0.005
    while True:
        ready = []
        not_ready = []
        for oid in oids:
            if store.is_ready(oid) or (poll is not None and poll(oid)):
                ready.append(oid)
            else:
                not_ready.append(oid)
        done = len(ready) >= num_ready or not not_ready
        if not done and deadline is not None and time.monotonic() >= deadline:
            done = True
        if done:
            # Reference semantics (CoreWorker::Wait): the ready list holds at
            # most num_ready entries; both lists preserve input order.
            chosen = set(ready[:num_ready])
            return ([o for o in oids if o in chosen],
                    [o for o in oids if o not in chosen])
        time.sleep(interval)
        interval = min(interval * 1.5, 0.05)
