"""Owner-side in-process object store.

Analogue of the reference's ``CoreWorkerMemoryStore``
(``src/ray/core_worker/store_provider/memory_store/memory_store.h:43``): every
process holds the values it owns (task returns, ``put`` objects) — or, for
values that landed in the node's shared-memory store, a locator — and serves
them to remote borrowers over its RPC server. Entries are created *pending*
at task-submission time and fulfilled when the task replies, so ``get`` is a
wait on an event, and remote processes can long-poll the owner.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.core.errors import ObjectFreedError, GetTimeoutError
from ray_tpu.core.ids import ObjectID


class _Entry:
    __slots__ = ("event", "data", "shm_ref", "shm_view", "shm_pin", "error",
                 "freed", "owned", "refcount", "zero_since", "nested")

    def __init__(self):
        self.event = threading.Event()
        self.data: Optional[bytes] = None      # serialized frame (inline path)
        self.shm_ref = None                    # shm locator dict (shm path)
        self.shm_view = None                   # pinned local ShmView, if open
        self.shm_pin = None                    # owner's primary-copy pin
        self.error: Optional[BaseException] = None  # submission-level failure
        self.freed = False
        self.owned = False        # True: this process owns the object
        self.refcount = 0         # cluster-wide handle count (owner-side)
        self.zero_since: Optional[float] = None  # when refcount hit <= 0
        # ObjectRefs nested inside this entry's serialized frame: held as
        # live handles so the inner objects can't be freed while the frame
        # is alive (cleared on free/drop).
        self.nested = None


class MemoryStore:
    def __init__(self):
        self._entries: Dict[ObjectID, _Entry] = {}
        self._lock = threading.Lock()
        # Inline serialized bytes held by this store (shm-resident
        # values are accounted by the node store). Maintained at the
        # put/free/drop sites so the metrics collector reads a plain
        # int instead of walking every entry.
        self._data_bytes = 0

    def data_bytes(self) -> int:
        with self._lock:
            return self._data_bytes

    def _entry(self, oid: ObjectID) -> _Entry:
        with self._lock:
            entry = self._entries.get(oid)
            if entry is None:
                entry = _Entry()
                self._entries[oid] = entry
            return entry

    def create_pending(self, oid: ObjectID) -> None:
        self._entry(oid).owned = True

    def reset_pending(self, oid: ObjectID) -> None:
        """Re-arm an entry for reconstruction: the producing task will be
        re-executed and fulfil it again (reference:
        object_recovery_manager.h:96 resubmit path)."""
        with self._lock:
            entry = self._entries.get(oid)
            if entry is None:
                entry = _Entry()
                self._entries[oid] = entry
            entry.owned = True
            if entry.data is not None:
                self._data_bytes -= len(entry.data)
            entry.data = None
            entry.shm_ref = None
            if entry.shm_view is not None:
                entry.shm_view.release()
                entry.shm_view = None
            if entry.shm_pin is not None:
                entry.shm_pin.release()
                entry.shm_pin = None
            entry.error = None
            entry.freed = False
            entry.nested = None
            entry.event.clear()

    def apply_ref_update(self, oid: ObjectID, delta: int) -> None:
        """Owner-side handle-count update from a borrower process (or this
        process's own tracker)."""
        with self._lock:
            entry = self._entries.get(oid)
            if entry is None:
                if delta <= 0:
                    return
                entry = _Entry()
                self._entries[oid] = entry
            entry.refcount += delta
            # delta == 0 means a handle lived and died within one tracker
            # flush window: the touch still (re)arms the zero clock.
            if entry.refcount <= 0:
                if entry.zero_since is None:
                    entry.zero_since = time.monotonic()
            else:
                entry.zero_since = None

    def sweep_dead_refs(self, grace_s: float):
        """Collect owned, ready objects whose handle count has been zero for
        longer than ``grace_s``. Returns the freed entries' (oid, shm_ref)
        pairs so the caller can propagate the free to the node store."""
        now = time.monotonic()
        victims = []
        with self._lock:
            for oid, entry in list(self._entries.items()):
                if (entry.owned and not entry.freed
                        and entry.refcount <= 0
                        and entry.zero_since is not None
                        and now - entry.zero_since > grace_s
                        and entry.event.is_set()):
                    victims.append((oid, entry.shm_ref))
        return victims

    def put_serialized(self, oid: ObjectID, data: bytes) -> None:
        entry = self._entry(oid)
        with self._lock:
            if entry.data is not None:
                self._data_bytes -= len(entry.data)
            entry.data = data
            self._data_bytes += len(data)
        entry.event.set()

    def put_shm(self, oid: ObjectID, shm_ref) -> None:
        entry = self._entry(oid)
        entry.shm_ref = shm_ref
        entry.event.set()

    def put_error(self, oid: ObjectID, error: BaseException) -> None:
        entry = self._entry(oid)
        entry.error = error
        entry.event.set()

    def is_ready(self, oid: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(oid)
        return entry is not None and entry.event.is_set()

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._entries

    def wait_ready(self, oid: ObjectID, timeout: Optional[float]) -> _Entry:
        entry = self._entry(oid)
        if not entry.event.wait(timeout):
            raise GetTimeoutError(
                f"Object {oid.hex()} not ready within {timeout}s")
        if entry.freed:
            raise ObjectFreedError(f"Object {oid.hex()} was freed")
        if entry.error is not None:
            raise entry.error
        return entry

    def put_shm_ref(self, oid: ObjectID, shm_ref: dict) -> None:
        entry = self._entry(oid)
        entry.shm_ref = shm_ref
        entry.event.set()

    def mark_owned(self, oid: ObjectID) -> None:
        self._entry(oid).owned = True

    def free(self, oid: ObjectID) -> None:
        with self._lock:
            entry = self._entries.get(oid)
            if entry is None:
                return
            if entry.data is not None:
                self._data_bytes -= len(entry.data)
            entry.data = None
            entry.shm_ref = None
            if entry.shm_view is not None:
                entry.shm_view.release()
                entry.shm_view = None
            if entry.shm_pin is not None:
                entry.shm_pin.release()
                entry.shm_pin = None
            entry.nested = None
            entry.freed = True
            if entry.zero_since is None:
                entry.zero_since = time.monotonic()
            entry.event.set()

    def drop(self, oid: ObjectID) -> None:
        """Release a borrower-cache entry entirely (pins, views, dict slot) so
        a later get re-pulls from the owner. No-op for owned objects."""
        with self._lock:
            entry = self._entries.get(oid)
            if entry is None or entry.owned:
                return
            if entry.shm_view is not None:
                entry.shm_view.release()
                entry.shm_view = None
            if entry.shm_pin is not None:
                entry.shm_pin.release()
                entry.shm_pin = None
            if entry.data is not None:
                self._data_bytes -= len(entry.data)
            entry.nested = None
            del self._entries[oid]

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            entry = self._entries.pop(oid, None)
            if entry is not None and entry.data is not None:
                self._data_bytes -= len(entry.data)

    def set_nested(self, oid: ObjectID, refs) -> None:
        if refs:
            self._entry(oid).nested = list(refs)

    def purge_freed(self, ttl_s: float) -> None:
        """Remove long-freed tombstones. A freed object's cluster-wide count
        was zero, so nothing should ask for it again; the tombstone only
        exists to turn late (out-of-band) gets into ObjectFreedError rather
        than a hang, and a TTL bounds that courtesy."""
        now = time.monotonic()
        with self._lock:
            dead = [oid for oid, e in self._entries.items()
                    if e.freed and e.zero_since is not None
                    and now - e.zero_since > ttl_s]
            for oid in dead:
                del self._entries[oid]

    def size(self) -> int:
        with self._lock:
            return len(self._entries)


def wait_any(
    store: MemoryStore,
    oids,
    num_ready: int,
    timeout: Optional[float],
    poll=None,
):
    """Block until ``num_ready`` of ``oids`` are ready locally (or ``poll``
    reports them ready remotely). Returns (ready, not_ready) preserving order.
    Used by ``api.wait`` (reference: ``CoreWorker::Wait``, core_worker.h:804).
    """
    deadline = None if timeout is None else time.monotonic() + timeout
    interval = 0.005
    while True:
        ready = []
        not_ready = []
        for oid in oids:
            if store.is_ready(oid) or (poll is not None and poll(oid)):
                ready.append(oid)
            else:
                not_ready.append(oid)
        done = len(ready) >= num_ready or not not_ready
        if not done and deadline is not None and time.monotonic() >= deadline:
            done = True
        if done:
            # Reference semantics (CoreWorker::Wait): the ready list holds at
            # most num_ready entries; both lists preserve input order.
            chosen = set(ready[:num_ready])
            return ([o for o in oids if o in chosen],
                    [o for o in oids if o not in chosen])
        time.sleep(interval)
        interval = min(interval * 1.5, 0.05)
