"""Per-node metrics agent: the push half of the cluster metrics pipeline
for processes that have no core worker.

Worker and driver processes already ship their registry to the
controller through the ``util.metrics`` flusher (it needs a connected
runtime for its source identity and controller link). A NODE supervisor
process that never calls ``init()`` — ``ray_tpu start`` worker boxes —
has a registry full of exactly the series this PR exists for (its
RpcServer's write-path counters, its heartbeat RTTs) and no one to push
them. The agent is that pusher: bounded cumulative snapshots over the
node's existing controller link, on the heartbeat cadence.

One process, one pusher: the registry's ``claim_pusher`` arbitration
makes the core-worker flusher always win (richest identity), and an
agent that loses ownership retracts its series with one final EMPTY
push — two pushers shipping the same registry under different source
keys would double every counter in the cluster view.

The agent's controller link is a ``ReconnectingClient``: a controller
restart costs retries, never the thread (mirrors the PR 9 flusher
robustness contract, pinned by tests/test_core_observability.py).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from ray_tpu.core.config import config
from ray_tpu.util.metrics import _Registry
from ray_tpu.util.ratelimit import log_every

logger = logging.getLogger(__name__)


class MetricsAgent:
    def __init__(self, controller_client, node_id_bytes: bytes,
                 period_s: Optional[float] = None):
        self._controller = controller_client
        self._source = {"node_id": node_id_bytes, "worker_id": b"",
                        "role": "node", "pid": os.getpid()}
        self._period = period_s
        self._owner = f"agent-{id(self)}"
        self._pushed_any = False
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="metrics-agent", daemon=True)
        self._thread.start()

    def _push(self, snapshot) -> bool:
        try:
            self._controller.notify("push_metrics", self._source, snapshot)
            return True
        except Exception:
            # Droppable (snapshots are cumulative; the next push
            # supersedes), but a push failing every beat means the head
            # is unreachable — leave a trail.
            log_every("metrics_agent.push", 60.0, logger,
                      "metrics agent push to controller failed",
                      exc_info=True)
            return False

    def push_once(self) -> bool:
        """One synchronous push (tests / shutdown flush). Respects the
        single-pusher arbitration."""
        from ray_tpu.core import runtime

        if runtime._core_worker is not None:
            return False
        if not _Registry.get().claim_pusher(self._owner):
            return False
        ok = self._push(_Registry.get().snapshot())
        self._pushed_any = self._pushed_any or ok
        return ok

    def _loop(self) -> None:
        while not self._stopped.wait(
                self._period if self._period is not None
                else config.heartbeat_period_s):
            from ray_tpu.core import runtime

            owns = (runtime._core_worker is None
                    and _Registry.get().claim_pusher(self._owner))
            if owns:
                ok = self._push(_Registry.get().snapshot())
                self._pushed_any = self._pushed_any or ok
            elif self._pushed_any:
                # Lost ownership to the core-worker flusher (an init()
                # landed in this process): retract our series so the
                # same registry isn't counted under two source keys.
                self._pushed_any = not self._push([])

    def stop(self) -> None:
        self._stopped.set()
        _Registry.get().release_pusher(self._owner)
